"""BERT sequence classification with BucketedDistributedSampler.

Capability config #5 (BASELINE.md): BERT-base seq-cls with the bucketed
sampler + gradient accumulation + clipping.  Demonstrates the data-level
long-sequence efficiency story of the reference (README.md:43-45): samples
are sorted by length, bucketed so each batch draws similar lengths, and
padded only to the batch max — minimizing wasted attention FLOPs.

Data: synthetic token sequences with length-dependent labels (so the loss is
learnable), lengths drawn from a long-tailed distribution to make bucketing
matter.  Swap in a real tokenized dataset by providing ``--data`` as an
``.npz`` with ``input_ids`` (object array of int sequences) and ``labels``.

Run:
    python train.py --size tiny --epochs 2            # CPU-friendly
    python train.py --size base --device tpu --precision bf16 --grad-accum 2
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import optax

from stoke_tpu import (
    BucketedDistributedSampler,
    ClipGradNormConfig,
    Stoke,
    StokeOptimizer,
)
from stoke_tpu.models import BertForSequenceClassification


class SyntheticSeqClsDataset:
    """Variable-length token sequences; label = parity of a keyword count, so
    the task is learnable from content, not length."""

    def __init__(self, n=4096, vocab=1000, min_len=8, max_len=128, seed=0):
        r = np.random.default_rng(seed)
        # long-tailed lengths (mostly short, few long — the bucketing case)
        lens = np.clip(
            (r.pareto(2.5, size=n) + 1.0) * min_len, min_len, max_len
        ).astype(int)
        self.seqs = [r.integers(5, vocab, size=L) for L in lens]
        self.labels = np.asarray(
            [int((s < 50).sum() % 2) for s in self.seqs], np.int64
        )

    def __len__(self):
        return len(self.seqs)

    def __getitem__(self, i):
        return self.seqs[i], self.labels[i]

    def lengths(self):
        return [len(s) for s in self.seqs]


# batch assembly (gather + pad-to-batch-max + mask) runs natively: the
# dataset is wrapped in RaggedSequenceDataset, whose loader path calls the
# C++ NativeBatcher.gather_pad in one GIL-free call per batch; max length is
# rounded to a multiple of 32 (bounds XLA recompilation, satisfies flash/
# ring divisibility)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="tiny", help="tiny/mini/small/medium/base/large")
    ap.add_argument("--device", default="cpu")
    ap.add_argument("--distributed", default=None)
    ap.add_argument("--precision", default=None)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--grad-accum", type=int, default=2)
    ap.add_argument("--buckets", type=int, default=4)
    ap.add_argument("--n-samples", type=int, default=4096)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--fsdp", action="store_true")
    ap.add_argument(
        "--attention", default="dense",
        choices=["dense", "flash", "ring", "ulysses"],
        help="dense softmax, pallas flash kernel, or sequence-parallel "
        "ring/Ulysses over a mesh seq axis",
    )
    ap.add_argument("--seq-par", type=int, default=2,
                    help="mesh seq-axis size for ring/ulysses")
    args = ap.parse_args()

    attention_fn = None
    mesh_cfgs = []
    if args.attention == "flash":
        from stoke_tpu.ops import make_flash_attention

        attention_fn = make_flash_attention()  # auto block sizing (512-pref ladder)
    elif args.attention in ("ring", "ulysses"):
        from stoke_tpu.configs import DeviceOptions, MeshConfig
        from stoke_tpu.ops import make_ring_attention, make_ulysses_attention
        from stoke_tpu.parallel import build_mesh

        mesh_cfg = MeshConfig(axes=("data", "seq"), shape=(-1, args.seq_par))
        mesh = build_mesh(mesh_cfg, DeviceOptions(args.device), True)
        maker = (
            make_ring_attention if args.attention == "ring"
            else make_ulysses_attention
        )
        attention_fn = maker(mesh, "seq", "data")
        mesh_cfgs = [mesh_cfg]
        if args.distributed is None:
            args.distributed = "dp"

    ds = SyntheticSeqClsDataset(n=args.n_samples)
    model_kwargs = {}
    if attention_fn is not None:
        model_kwargs = {"attention_fn": attention_fn, "dropout_rate": 0.0}
    model = BertForSequenceClassification(
        vocab_size=1000, num_classes=2, size_name=args.size, max_len=256,
        **model_kwargs,
    )
    from stoke_tpu import init_module

    variables = init_module(
        model,
        jax.random.PRNGKey(0),
        np.zeros((2, 16), np.int32),
        np.ones((2, 16), np.int32),
        train=False,
    )

    stoke = Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.adamw, optimizer_kwargs={"learning_rate": args.lr}
        ),
        loss=lambda logits, labels: optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean(),
        params=variables,
        batch_size_per_device=args.batch_size,
        grad_accum=args.grad_accum,
        grad_clip=ClipGradNormConfig(max_norm=1.0),
        device=args.device,
        distributed=args.distributed,
        precision=args.precision,
        fsdp=args.fsdp,
        configs=mesh_cfgs,
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
    )

    from stoke_tpu import RaggedSequenceDataset

    ragged = RaggedSequenceDataset(ds.seqs, ds.labels, pad_multiple=32)
    # sort by length → bucket → similar-length batches (reference README.md:43-45)
    world = stoke.world_size
    per_process = stoke.batch_size * (world // max(stoke.n_processes, 1))
    sampler = BucketedDistributedSampler(
        ragged,
        buckets=args.buckets,
        batch_size=per_process,
        sorted_idx=ragged.sorted_idx(),
        num_replicas=stoke.n_processes,
        rank=stoke.rank,
    )
    loader = stoke.DataLoader(ragged, sampler=sampler)

    for epoch in range(args.epochs):
        loader.set_epoch(epoch)
        t0, n_tok, n_seq, correct = time.time(), 0, 0, 0
        for inputs, labels in loader:
            out = stoke.model(
                inputs["input_ids"], inputs["attention_mask"]
            )
            loss = stoke.loss(out, labels)
            stoke.backward(loss)
            stoke.step()
            n_tok += int(np.asarray(inputs["attention_mask"]).sum())
            n_seq += labels.shape[0]
        stoke.block_until_ready()
        dt = time.time() - t0
        stoke.print_on_devices(
            f"epoch {epoch}: {dt:.1f}s ({n_seq / dt:.0f} seq/s, "
            f"{n_tok / dt:.0f} real tok/s) ema_loss={stoke.ema_loss:.4f}"
        )


if __name__ == "__main__":
    main()

"""GPT causal language modeling example.

Decoder-only LM on synthetic structured text (arithmetic-progression token
streams), demonstrating the causal-attention options: dense, pallas flash
(``--attention flash``), or ring sequence parallelism for long context
(``--attention ring --seq-par N``), plus fsdp/bf16/grad-accum flags — the
same declarative switches as the CIFAR and BERT examples.

Run:
    python train.py --size tiny --epochs 2                  # CPU-friendly
    python train.py --size base --device tpu --precision bf16 --attention flash
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import optax

from stoke_tpu import (
    ArrayDataset,
    ClipGradNormConfig,
    Stoke,
    StokeOptimizer,
    init_module,
)
from stoke_tpu.models import GPT, causal_lm_loss


def make_corpus(n=2048, seq_len=128, vocab=64, seed=0):
    """Arithmetic progressions mod vocab: next token is fully predictable
    from the previous two, so the LM loss has a known floor near zero."""
    r = np.random.default_rng(seed)
    start = r.integers(0, vocab, size=(n, 1))
    stride = r.integers(1, 7, size=(n, 1))
    pos = np.arange(seq_len)[None, :]
    return ((start + stride * pos) % vocab).astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="tiny")
    ap.add_argument("--device", default="cpu")
    ap.add_argument("--distributed", default=None)
    ap.add_argument("--precision", default=None)
    ap.add_argument("--attention", default="dense", choices=["dense", "flash", "ring"])
    ap.add_argument("--seq-par", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--grad-accum", type=int, default=1)
    ap.add_argument("--n-samples", type=int, default=2048)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--fsdp", action="store_true")
    ap.add_argument("--telemetry", default=None, metavar="DIR",
                    help="enable the unified telemetry pipeline with this "
                    "output dir (JSONL step events incl. tokens/sec, "
                    "Prometheus exposition, recompile/HBM tracking — "
                    "docs/observability.md)")
    ap.add_argument("--health", action="store_true",
                    help="enable the training health monitor: on-device "
                    "numerics sentinels, anomaly detectors, and a crash "
                    "flight recorder writing post-mortem bundles under the "
                    "telemetry dir (requires --telemetry; docs/"
                    "observability.md \"Training health & post-mortems\")")
    ap.add_argument("--serve", type=int, default=0, metavar="N",
                    help="after training, serve N prompts from the corpus "
                    "through the continuous-batching engine (paged "
                    "KV-cache, prefill/decode split — docs/serving.md) "
                    "and report TTFT/TPOT plus how many continuations "
                    "match the true arithmetic progression")
    ap.add_argument("--serve-quant", default="none",
                    choices=["none", "bf16", "int8"],
                    help="weight quantization for the --serve engine")
    args = ap.parse_args()
    if args.health and not args.telemetry:
        ap.error("--health requires --telemetry DIR (sentinels surface "
                 "through the telemetry step events)")
    if args.serve and args.attention == "ring":
        ap.error("--serve supports dense/flash attention (the serving "
                 "engine runs single-host; ring is the training-side "
                 "sequence-parallel transform)")

    attention_fn, is_causal, mesh_cfgs = None, False, []
    if args.attention == "flash":
        from stoke_tpu.ops import make_flash_attention

        attention_fn = make_flash_attention(causal=True)  # auto block sizing
        is_causal = True
    elif args.attention == "ring":
        from stoke_tpu.configs import DeviceOptions, MeshConfig
        from stoke_tpu.ops import make_ring_attention
        from stoke_tpu.parallel import build_mesh

        mesh_cfg = MeshConfig(axes=("data", "seq"), shape=(-1, args.seq_par))
        mesh = build_mesh(mesh_cfg, DeviceOptions(args.device), True)
        attention_fn = make_ring_attention(mesh, "seq", "data", causal=True)
        is_causal = True
        mesh_cfgs = [mesh_cfg]
        if args.distributed is None:
            args.distributed = "dp"

    model_kwargs = dict(dropout_rate=0.0) if attention_fn else {}
    if attention_fn:
        model_kwargs.update(attention_fn=attention_fn, attention_is_causal=is_causal)
    model = GPT(vocab_size=64, size_name=args.size, max_len=args.seq_len,
                **model_kwargs)
    corpus = make_corpus(args.n_samples, args.seq_len)
    variables = init_module(model, jax.random.PRNGKey(0), corpus[:2], train=False)

    configs = list(mesh_cfgs)
    if args.telemetry:
        from stoke_tpu import TelemetryConfig

        configs.append(TelemetryConfig(
            output_dir=args.telemetry, log_every_n_steps=10, tensorboard=True,
            grad_norm=args.health,
        ))
    if args.health:
        from stoke_tpu import HealthConfig

        configs.append(HealthConfig())
    serve_pad = serve_max_len = None
    if args.serve:
        from stoke_tpu import ServeConfig

        # the padding bucket must round a full prompt UP without passing
        # the model's position table: round max_seq_len DOWN to the
        # bucket (e.g. --seq-len 100 -> bucket 32, serve cap 96)
        serve_pad = min(32, args.seq_len)
        serve_max_len = (args.seq_len // serve_pad) * serve_pad
        configs.append(ServeConfig(
            max_seqs=8,
            kv_block_size=16,
            max_seq_len=serve_max_len,
            max_new_tokens=16,
            prefill_pad_multiple=serve_pad,
            attention="flash" if args.attention == "flash" else "dense",
            quant=args.serve_quant,
        ))
    stoke = Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.adamw, optimizer_kwargs={"learning_rate": args.lr}
        ),
        loss=causal_lm_loss,
        params=variables,
        batch_size_per_device=args.batch_size,
        grad_accum=args.grad_accum,
        grad_clip=ClipGradNormConfig(max_norm=1.0),
        device=args.device,
        distributed=args.distributed,
        precision=args.precision,
        fsdp=args.fsdp,
        configs=configs,
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
    )
    loader = stoke.DataLoader(ArrayDataset(corpus), shuffle=True, drop_last=True)
    for epoch in range(args.epochs):
        t0, n_tok = time.time(), 0
        for batch in loader:
            stoke.train_step(batch, batch)
            step_tokens = batch.shape[0] * batch.shape[1]
            n_tok += step_tokens
            if args.telemetry:
                # feed tokens/sec into the step events (data/tokens_total)
                stoke.telemetry.add_tokens(step_tokens)
        stoke.block_until_ready()
        dt = time.time() - t0
        stoke.print_on_devices(
            f"epoch {epoch}: {dt:.1f}s ({n_tok / dt:.0f} tok/s) "
            f"ema_loss={stoke.ema_loss:.4f}"
        )
    if args.health:
        stoke.print_on_devices(
            f"health: {stoke.health.anomaly_count} anomalies "
            f"({stoke.health.anomaly_counts_by_detector() or 'clean run'})"
        )
    if args.serve:
        # serve the model we just trained: prompts are progression
        # prefixes, so a converged LM's greedy continuation should BE the
        # progression — serving quality is directly checkable
        engine = stoke.serve()
        r = np.random.default_rng(1)
        n_gen = min(16, serve_max_len // 2)
        prompts, truths = [], []
        for _ in range(args.serve):
            row = corpus[int(r.integers(0, corpus.shape[0]))]
            cut = int(r.integers(min(8, serve_max_len - n_gen - 1),
                                 serve_max_len - n_gen))
            prompts.append(row[:cut])
            truths.append(row[cut : cut + n_gen])
        streams = engine.generate(prompts, max_new_tokens=n_gen)
        exact = sum(
            int(np.array_equal(np.array(s), t))
            for s, t in zip(streams, truths)
        )
        s = engine.summary()
        stoke.print_on_devices(
            f"serve: {args.serve} requests, {s['tokens_out']:.0f} tokens, "
            f"{exact}/{args.serve} continuations exactly match the "
            f"progression | ttft p50 {s['ttft_p50_s'] * 1e3:.1f}ms "
            f"p99 {s['ttft_p99_s'] * 1e3:.1f}ms, tpot p50 "
            f"{(s['tpot_p50_s'] or 0) * 1e3:.1f}ms | quant "
            f"{args.serve_quant} ({s['quant']['compression']:.2f}x)"
        )
    if args.telemetry:
        stoke.close_telemetry()


if __name__ == "__main__":
    main()

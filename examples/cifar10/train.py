"""CIFAR-10 training example: the reference demo story on TPU.

Mirrors the reference example (examples/cifar10/train.py:24-186): a
YAML-preset-driven CLI where flipping config flags switches device /
distributed / precision / sharding context while the training loop stays
identical.  The reference ships 8 YAML presets spanning its backend matrix
(examples/cifar10/config/*.yaml); the presets in ``config/`` here cover the
same capability ladder on TPU (see config/README inside each file header).

Data: real CIFAR-10 if a ``cifar-10-batches-py`` directory is supplied (the
standard pickled batches), else deterministic synthetic CIFAR-shaped data —
this environment has no network egress.

Run:
    python train.py --config config/tpu_bf16.yaml
    python train.py --config config/dp_fsdp_bf16.yaml --epochs 2
"""

from __future__ import annotations

import argparse
import os
import pickle
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import optax
import yaml

from stoke_tpu import (
    ClipGradNormConfig,
    FSDPConfig,
    OSSConfig,
    SDDPConfig,
    Stoke,
    StokeOptimizer,
)
from stoke_tpu.models import BasicNN, ResNet50


class CIFAR10:
    """Map-style CIFAR-10: real pickled batches when available, else
    deterministic synthetic data with learnable structure (class-dependent
    means) so loss curves are meaningful."""

    def __init__(self, root=None, train=True, n_synth=10000, seed=0):
        if root and os.path.isdir(root):
            xs, ys = [], []
            names = (
                [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
            )
            for nm in names:
                with open(os.path.join(root, nm), "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                xs.append(d[b"data"])
                ys.extend(d[b"labels"])
            x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            self.x = (x.astype(np.float32) / 255.0 - 0.5) / 0.5
            self.y = np.asarray(ys, np.int64)
        else:
            r = np.random.default_rng(seed if train else seed + 1)
            self.y = r.integers(0, 10, size=(n_synth,))
            means = r.normal(size=(10, 1, 1, 3)).astype(np.float32)
            self.x = (
                r.normal(size=(n_synth, 32, 32, 3)).astype(np.float32) * 0.5
                + means[self.y]
            )

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def cross_entropy(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def build_stoke(cfg: dict) -> Stoke:
    model_name = cfg.get("model", "basic")
    if model_name == "basic":
        model = BasicNN()
    elif model_name == "resnet50":
        model = ResNet50(num_classes=10, cifar_stem=True)
    else:
        raise ValueError(f"unknown model {model_name}")
    from stoke_tpu import init_module

    variables = init_module(
        model,
        jax.random.PRNGKey(cfg.get("seed", 0)),
        np.zeros((2, 32, 32, 3), np.float32),
        train=False,
    )
    configs = []
    if cfg.get("fsdp"):
        configs.append(FSDPConfig(min_weight_size=2**12))
    if cfg.get("oss"):
        configs.append(OSSConfig())
    if cfg.get("sddp"):
        configs.append(SDDPConfig())
    if cfg.get("telemetry"):
        # telemetry: {output_dir: runs/exp/telemetry, log_every_n_steps: 10}
        # — or just `telemetry: true` for the defaults (docs/observability.md)
        from stoke_tpu import TelemetryConfig

        spec = cfg["telemetry"]
        configs.append(
            TelemetryConfig(**spec) if isinstance(spec, dict)
            else TelemetryConfig()
        )
    if cfg.get("comm"):
        # comm: {dtype: int8, bucket_mb: 25, error_feedback: true} — or
        # `comm: int8` shorthand.  Quantized gradient collectives with
        # error feedback (docs/sharding.md "Quantized gradient collectives")
        from stoke_tpu import CommConfig

        spec = cfg["comm"]
        configs.append(
            CommConfig(**spec) if isinstance(spec, dict)
            else CommConfig(dtype=str(spec))
        )
    if cfg.get("health"):
        # health: {watchdog: true, watchdog_timeout_s: 300} — or just
        # `health: true` for the defaults.  Training health monitor:
        # on-device sentinels + anomaly detectors + crash flight recorder
        # (docs/observability.md "Training health & post-mortems").
        # Requires the telemetry block (status-validated).
        from stoke_tpu import HealthConfig

        spec = cfg["health"]
        configs.append(
            HealthConfig(**spec) if isinstance(spec, dict)
            else HealthConfig()
        )
    return Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.sgd,
            optimizer_kwargs={
                "learning_rate": cfg.get("lr", 0.01),
                "momentum": cfg.get("momentum", 0.9),
            },
        ),
        loss=cross_entropy,
        params=variables,
        batch_size_per_device=cfg.get("batch_size_per_device", 32),
        grad_accum=cfg.get("grad_accum", 1),
        grad_clip=ClipGradNormConfig(max_norm=cfg["grad_clip_norm"])
        if cfg.get("grad_clip_norm")
        else None,
        device=cfg.get("device", "cpu"),
        distributed=cfg.get("distributed"),
        precision=cfg.get("precision"),
        oss=bool(cfg.get("oss")),
        sddp=bool(cfg.get("sddp")),
        fsdp=bool(cfg.get("fsdp")),
        configs=configs,
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        seed=cfg.get("seed", 0),
    )


def evaluate(stoke: Stoke, loader) -> float:
    stoke.eval()
    correct = total = 0
    for x, y in loader:
        logits = stoke.model(x)
        correct += int((np.argmax(np.asarray(logits), -1) == np.asarray(y)).sum())
        total += int(np.asarray(y).shape[0])
    stoke.train()
    return correct / max(total, 1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", required=True)
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--data", default=None, help="path to cifar-10-batches-py")
    ap.add_argument("--synthetic-n", type=int, default=10000)
    args = ap.parse_args()
    with open(args.config) as f:
        cfg = yaml.safe_load(f)
    if args.epochs is not None:
        cfg["epochs"] = args.epochs

    from stoke_tpu import ArrayDataset

    stoke = build_stoke(cfg)
    train_raw = CIFAR10(args.data, train=True, n_synth=args.synthetic_n)
    test_raw = CIFAR10(args.data, train=False, n_synth=args.synthetic_n // 5)
    # ArrayDataset routes batch assembly through the native C++ batcher
    train_ds = ArrayDataset(train_raw.x, train_raw.y)
    test_ds = ArrayDataset(test_raw.x, test_raw.y)
    train_loader = stoke.DataLoader(train_ds, shuffle=True, drop_last=True)
    test_loader = stoke.DataLoader(test_ds, drop_last=True)

    if len(train_loader) == 0:
        raise SystemExit(
            f"dataset too small: {len(train_ds)} samples yield zero "
            f"{train_loader.batch_size}-sample global batches; raise "
            f"--synthetic-n or lower batch_size_per_device"
        )
    stoke.print_on_devices(
        f"train={len(train_ds)} test={len(test_ds)} "
        f"effective_batch={stoke.effective_batch_size}"
    )
    base_acc = evaluate(stoke, test_loader)
    stoke.print_on_devices(f"baseline accuracy: {base_acc:.4f}")

    for epoch in range(cfg.get("epochs", 2)):
        t0 = time.time()
        n_img = 0
        for x, y in train_loader:
            out = stoke.model(x)
            loss = stoke.loss(out, y)
            stoke.backward(loss)
            stoke.step()
            n_img += x.shape[0]
        stoke.block_until_ready()
        dt = time.time() - t0
        acc = evaluate(stoke, test_loader)
        stoke.print_on_devices(
            f"epoch {epoch}: {dt:.1f}s ({n_img / dt:.0f} img/s) "
            f"ema_loss={stoke.ema_loss:.4f} test_acc={acc:.4f}"
        )
    if cfg.get("save_path"):
        stoke.save(cfg["save_path"], name=cfg.get("model", "basic"))


if __name__ == "__main__":
    main()

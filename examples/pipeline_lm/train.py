"""Pipeline-parallel causal LM trained through ``Stoke.train_steps``.

Runnable demonstration of the dp×pp composition at framework level: a
decoder-only LM whose transformer blocks are split over 4 pipeline stages
(``PipelinedLM``), with the remaining mesh axis data-parallel, driven by the
multi-step scanned ``train_steps`` fast path (N optimizer steps per
compiled dispatch — the dispatch-amortization that matters on real TPU
links).

Hermetic by default — simulated 8-device CPU mesh, tiny shapes:

    env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/pipeline_lm/train.py

On a TPU slice, drop the env overrides and scale --batch/--seq-len/--size.
Schedule characterization numbers (bubble fraction vs microbatches/rounds):
docs/sharding.md, measured by scripts/bench_pipeline.py.
"""

from __future__ import annotations

import argparse
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--rounds", type=int, default=1,
                    help="virtual stages per device (circular schedule)")
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--size", default="tiny")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--segment", type=int, default=5,
                    help="optimizer steps per train_steps dispatch")
    args = ap.parse_args()

    import jax
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from stoke_tpu import (
        MeshConfig,
        PartitionRulesConfig,
        Stoke,
        StokeOptimizer,
    )
    from stoke_tpu.models import (
        PipelinedLM,
        causal_lm_loss,
        pipeline_parallel_rules,
    )

    n = len(jax.devices())
    S = args.stages
    assert n % S == 0, f"{n} devices not divisible by {S} stages"
    dp = n // S
    mesh = Mesh(np.asarray(jax.devices()).reshape(dp, S), ("data", "stage"))
    print(f"mesh: dp{dp}×pp{S} over {n} {jax.devices()[0].platform} devices, "
          f"rounds={args.rounds}")

    if args.batch % (args.microbatches * max(dp, 1)) != 0:
        raise SystemExit(
            f"--batch {args.batch} must be divisible by microbatches×dp = "
            f"{args.microbatches}×{dp} (each microbatch's rows shard over "
            f"the data axis)"
        )
    adapter = PipelinedLM(
        mesh,
        vocab_size=256,
        size_name=args.size,
        max_len=args.seq_len,
        num_microbatches=args.microbatches,
        rounds=args.rounds,
        data_axis="data" if dp > 1 else None,
    )
    variables = adapter.init(jax.random.PRNGKey(0))
    stoke = Stoke(
        model=adapter,
        optimizer=StokeOptimizer(
            optimizer=optax.adam, optimizer_kwargs={"learning_rate": 3e-3}
        ),
        loss=causal_lm_loss,
        params=variables,
        batch_size_per_device=max(1, args.batch // n),
        distributed="dp",
        configs=[
            MeshConfig(axes=("data", "stage"), shape=(dp, S)),
            PartitionRulesConfig(rules=pipeline_parallel_rules()),
        ],
        verbose=False,
    )
    w = stoke.params["stages"]
    lead = jax.tree_util.tree_leaves(w)[0]
    print(f"stage-stacked params: lead dim {lead.shape[0]} "
          f"(= rounds×stages), sharding {lead.sharding.spec}")

    # learnable data: a small pool of FIXED sequences (the model memorizes
    # their next-token structure; fresh random tokens would sit at the
    # ln(vocab) entropy floor forever)
    r = np.random.default_rng(0)
    seg = args.segment
    pool = r.integers(1, 256, size=(16, args.seq_len)).astype(np.int32)

    def make_segment():
        idx = r.integers(0, len(pool), size=(seg, args.batch))
        return pool[idx]

    t0 = time.perf_counter()
    first = last = None
    done = 0
    while done < args.steps:
        seqs = make_segment()
        reports = stoke.train_steps(seqs, (seqs,))
        losses = np.asarray(jax.device_get(reports)).reshape(seg, -1)
        if first is None:
            first = float(losses[0].mean())
        last = float(losses[-1].mean())
        done += seg
        print(f"step {stoke.optimizer_steps:4d}  loss {last:.4f}  "
              f"({seg} steps/dispatch)")
    dt = time.perf_counter() - t0
    toks = args.steps * args.batch * args.seq_len
    print(f"trained {args.steps} steps in {dt:.2f}s "
          f"({toks / dt:,.0f} tok/s incl. compile) — "
          f"loss {first:.4f} → {last:.4f}")
    assert last < first, "loss must decrease on the copy task"
    print("OK")


if __name__ == "__main__":
    main()

"""Benchmark: CIFAR-10 ResNet-50 training throughput through the Stoke facade.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Measures steady-state images/sec of the full framework path (multi-step
scanned facade API, bf16 precision policy) on whatever accelerator JAX
exposes (the driver runs this on one real TPU chip).

Measurement ledger: every successful on-accelerator measurement is persisted
to ``BENCH_RESULTS.json`` (value + date + methodology).  The TPU in this
environment is reached through a single-client remote tunnel that wedges for
long stretches; when a fresh measurement is impossible at capture time, the
emitted ``value`` is the persisted last verified on-chip number — flagged
with ``"fresh": false``, ``"stale": true``, the measurement date, and the
capture error — so the official record reflects what the framework
measurably does on the chip rather than the tunnel's state at capture time.
A 0.0 is emitted only if there has never been a successful on-chip
measurement.

Contract note (ADVICE r3): any consumer treating ``value`` as *this run's*
measurement must gate on ``fresh: true``; a ``fresh: false`` line is a
re-citation of the ledger, never a new data point.  Substitution is further
restricted to ledger records whose ``backend`` field (or legacy ``source``
text) proves an accelerator capture — a CPU-backed record is never emitted
as the on-chip headline.

Baseline: the reference publishes no numbers (BASELINE.md); the north star is
"CIFAR-10 ResNet-50 per-chip throughput matching an A100 running the
reference under DDP+AMP".  ``A100_BASELINE_IMGS_PER_SEC`` encodes that
comparison point as a fixed constant (estimate for ResNet-50 @ 32x32 CIFAR,
batch 256, AMP, single A100 — CIFAR images are ~50x cheaper than ImageNet's
224x224, so this is far above ImageNet-scale numbers).  ``vs_baseline`` is
value / baseline (>1.0 = faster than the A100 estimate).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

A100_BASELINE_IMGS_PER_SEC = 20000.0
#: serve-arm comparison point (ISSUE 9): rough tokens/s of a GPT-small-class
#: model under continuous batching on one A100 (vLLM-style paged serving,
#: greedy decode, mixed 8-64 token prompts) — the same "fixed constant
#: estimate" role A100_BASELINE_IMGS_PER_SEC plays for the training headline
A100_BASELINE_SERVE_TOKENS_PER_SEC = 2000.0
#: serve roofline ceilings (ISSUE 18): datasheet v5e bf16 matmul peak and
#: HBM bandwidth — what the serve cost columns (serve_mfu, hbm_bw_util,
#: attainable_tpot_s) are computed against.  Host-side accounting only:
#: the observatory never enters a program argument list, so the tokens/s
#: headline is unaffected
V5E_PEAK_TFLOPS = 197.0
V5E_PEAK_HBM_GBPS = 819.0
WATCHDOG_SECONDS = 1500
PROBE_TIMEOUT = 120
PROBE_ATTEMPTS = 3
PROBE_BACKOFF_SECONDS = 45

_REPO = os.path.dirname(os.path.abspath(__file__))
RESULTS_PATH = os.path.join(_REPO, "BENCH_RESULTS.json")
METRIC = "cifar10_resnet50_bf16_train_throughput"


def _load_results() -> dict:
    try:
        with open(RESULTS_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def persist_result(metric: str, record: dict, *, keep_best: bool = False) -> None:
    """Record a verified measurement in the BENCH_RESULTS.json ledger
    (public: scripts/accuracy_run.py persists its gate numbers here too).

    ``keep_best=True`` centralizes the higher-is-better guard every probe
    needs: a slower configuration (e.g. a sweep arm) never clobbers a
    faster verified record of the same metric.  (accuracy_run.py keeps its
    own backend/precision-ranked variant — value alone is not its order.)
    """
    results = _load_results()
    if keep_best and record.get("value", 0.0) <= results.get(
        metric, {}
    ).get("value", 0.0):
        return
    results[metric] = record
    tmp = RESULTS_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(results, f, indent=2)
        f.write("\n")
    os.replace(tmp, RESULTS_PATH)


_persist_result = persist_result  # internal alias


def record_backend(rec: dict) -> str:
    """Best-effort backend of a ledger record: the structured ``backend``
    field when present, else inferred from legacy free-text fields (records
    written before ADVICE r3 added the field)."""
    if rec.get("backend"):
        return rec["backend"]
    text = " ".join(
        str(rec.get(k, "")) for k in ("source", "note")
    ).lower()
    if "cpu" in text and "tpu" not in text:
        return "cpu"
    return "tpu" if "tpu" in text or "chip" in text else "unknown"


#: requested-config keys whose ABSENCE from a ledger record means the
#: record was captured at the named default (pre-ISSUE-13 serve records
#: were all reference-kernel greedy Poisson traces) — normalizing makes
#: the stale-substitution guard symmetric: a default run refuses a
#: pallas/topp/long-prompt capture exactly as an explicit pallas request
#: refuses a reference record
_SERVE_KEY_DEFAULTS = {
    "serve_decode_kernel": "reference",
    "serve_sampling": "greedy",
    "serve_long_prompt": False,
    # pre-ISSUE-16 serve records carried no SLO-tagged requests
    "serve_priority_mix": False,
    # pre-ISSUE-17 serve records were all non-speculative single-token
    # decode captures
    "serve_speculative": False,
    # pre-ISSUE-19 records (train AND serve — the key is shared) carried
    # no HBM capacity ledger
    "memory": False,
    # pre-ISSUE-20 serve records ran with no ops plane attached (no
    # scrape-under-load poller during the measured pass)
    "serve_scrape": False,
}


def _emit_persisted(metric: str, capture_error: str,
                    requested: dict | None = None) -> int:
    """Emit the last verified on-chip measurement as the official value.

    Returns the process exit code: 0 when a persisted measurement exists
    (the record is real, only the capture is stale), 1 only when the metric
    has never been successfully measured.  ``requested`` carries the run's
    explicit --api/--batch selections: a persisted record measured under a
    DIFFERENT configuration is never substituted for it.
    """
    rec = _load_results().get(metric)
    if rec and record_backend(rec) in ("cpu", "unknown"):
        capture_error += (
            f" [persisted record not applicable: backend is "
            f"{record_backend(rec)!r}, not a proven accelerator capture — "
            f"never substituted as the on-chip headline]"
        )
        rec = None
    if rec and requested:
        for key, want in requested.items():
            have = rec.get(key)
            if have is None and key in _SERVE_KEY_DEFAULTS:
                have = _SERVE_KEY_DEFAULTS[key]
            if want is not None and have != want:
                capture_error += (
                    f" [persisted record not applicable: measured with "
                    f"{key}={have!r}, run requested {key}={want!r}]"
                )
                rec = None
                break
    if rec and rec.get("value", 0) > 0:
        # serve records are tokens/s against the serving baseline — the
        # training imgs/s constant would misreport them 10x low
        baseline = (
            A100_BASELINE_SERVE_TOKENS_PER_SEC
            if rec.get("serve")
            else A100_BASELINE_IMGS_PER_SEC
        )
        # a stale emit must be self-describing (ISSUE 13 satellite): the
        # capture date of the value being restated rides the row as
        # stale_since AND in the human-read note, so "9257 imgs/s/chip
        # (stale since 2026-07-29)" needs no tribal knowledge to decode
        stale_since = rec.get("date") or "unknown date"
        out = {
            "metric": metric,
            "value": rec["value"],
            "unit": rec.get(
                "unit", "tokens/sec" if rec.get("serve") else "imgs/sec/chip"
            ),
            "vs_baseline": round(rec["value"] / baseline, 4),
            "fresh": False,
            "stale": True,
            "stale_since": rec.get("date"),
            "backend": record_backend(rec),
            "measured_on": rec.get("date"),
            "measured_by": rec.get("source", "bench.py"),
            "api": rec.get("api"),
            "batch": rec.get("batch"),
            "steps_per_dispatch": rec.get("steps_per_dispatch"),
            "xla_flags": rec.get("xla_flags"),
            "comm_dtype": rec.get("comm_dtype"),
            "comm_shard_tier": rec.get("comm_shard_tier"),
            # serve columns ride the stale emit too (absent for training
            # records): consumers of a re-cited serve capture still see
            # its latency/occupancy/quant descriptor
            **(
                {
                    k: rec.get(k)
                    for k in (
                        "serve", "serve_quant", "serve_max_seqs",
                        "serve_decode_kernel", "serve_prefill_chunk",
                        "serve_sampling", "serve_long_prompt",
                        "serve_priority_mix", "serve_speculative",
                        "serve_scrape", "scrape_polls",
                        "scrape_tpot_delta_frac", "scrape_overhead_ok",
                        "spec_accept_rate",
                        "accepted_tokens_per_dispatch",
                        "effective_tpot_s",
                        "decode_dispatches", "decode_dispatches_baseline",
                        "tpot_stall_chunked_s", "tpot_stall_unchunked_s",
                        "slo_attainment_interactive",
                        "slo_attainment_batch",
                        "slo_goodput_tokens_per_s",
                        "slo_goodput_tokens_per_s_interactive",
                        "slo_goodput_tokens_per_s_batch",
                        "ttft_p50_s", "ttft_p99_s", "tpot_p50_s",
                        "tpot_p99_s", "batch_fill_mean",
                        "kv_occupancy_peak", "quant_compression",
                        "quant_err_max", "quant_err_layer",
                        "serve_mfu", "hbm_bw_util", "flops_per_token",
                        "attainable_tpot_s",
                        "memory", "mem_resident_bytes",
                        "mem_temp_peak_bytes", "mem_headroom_frac",
                    )
                }
                if rec.get("serve")
                else {}
            ),
            "capture_error": capture_error,
            "note": f"persisted on-chip measurement, stale since "
            f"{stale_since} (fresh capture failed; see capture_error and "
            f"BENCH_NOTES.md)",
        }
        print(json.dumps(out))
        return 0
    print(
        json.dumps(
            {
                "metric": metric,
                "value": 0.0,
                "unit": "imgs/sec/chip",
                "vs_baseline": 0.0,
                "error": capture_error,
                "note": "no persisted on-chip measurement exists yet",
            }
        )
    )
    return 1


#: a fresh capture this far below the ledger best is flagged as a regression
REGRESSION_TOLERANCE = 0.05


#: capture-config keys whose mismatch vs the ledger best marks a comparison
#: as cross-configuration (A/B arms, seg sweeps) rather than a like-for-like
#: regression
_REGRESSION_CONFIG_KEYS = (
    "xla_flags", "steps_per_dispatch", "comm_dtype", "comm_shard_tier",
    "health", "attribution", "fleet", "tuned", "resilience", "trace",
    "numerics", "memory", "serve", "serve_quant", "serve_max_seqs",
    "serve_decode_kernel", "serve_prefill_chunk", "serve_sampling",
    "serve_long_prompt", "serve_priority_mix", "serve_speculative",
    "serve_scrape",
)


def check_regression(
    metric: str, value: float, config: dict | None = None
) -> dict | None:
    """Compare a FRESH capture against the ledger best for ``metric``.

    Returns a regression descriptor when ``value`` is more than
    ``REGRESSION_TOLERANCE`` below the best verified record (so a slower
    round surfaces the round it happens — VERDICT r4 item 8), else None.
    Records measured under a different api/batch are still comparable: the
    ledger best IS the headline the metric is judged by.

    ``config`` carries this capture's ``xla_flags``/``steps_per_dispatch``;
    when those differ from the ledger best's the descriptor is tagged
    ``config_differs: true`` (with both configurations inlined) — an A/B
    arm or seg-sweep running slower than a differently-configured best is
    an expected experiment outcome, not a like-for-like REGRESSION, and
    consumers should not alarm on it (ADVICE low).
    """
    best_rec = _load_results().get(metric, {})
    best = best_rec.get("value", 0.0)
    if best > 0 and value < best * (1.0 - REGRESSION_TOLERANCE):
        out = {
            "best": best,
            "ratio": round(value / best, 4),
            "note": f"fresh capture regressed >{REGRESSION_TOLERANCE:.0%} "
            f"below the ledger best ({value} vs {best})",
        }
        if config is not None:
            differing = {
                key: {"capture": config.get(key), "best": best_rec.get(key)}
                for key in _REGRESSION_CONFIG_KEYS
                if config.get(key) != best_rec.get(key)
            }
            if differing:
                out["config_differs"] = True
                out["config_diff"] = differing
                out["note"] += (
                    " [capture and ledger-best configurations differ "
                    "(A/B arm?); not a like-for-like regression]"
                )
        return out
    return None


def _serve_metric_name(preset: str, quant: str | None) -> str:
    """Serve-arm metric id: model size follows the preset, lossy-weight
    serving carries a quant suffix (a distinct metric for the
    stale-substitution and regression guards, like the comm arms)."""
    size = "tiny" if preset == "tiny" else "small"
    name = f"gpt_{size}_serve_throughput"
    if quant and quant != "none":
        name += f"_quant_{quant}"
    return name


def _missing_flag_tokens(requested: str, env_flags: str) -> list:
    """The whitespace-split tokens of ``requested`` not already present
    in ``env_flags`` — exact-token comparison, because a substring test
    would treat ``--flag=1`` as present inside an ambient ``--flag=16``
    and silently skip exporting it (a mislabeled measurement)."""
    if not requested:
        return []
    env_tokens = set(env_flags.split())
    return [t for t in requested.split() if t not in env_tokens]


#: sentinel: probe succeeded but only the CPU backend is visible
_CPU_ONLY = "cpu-only"

#: single-client tunnel coordination lock shared with scripts/tpu_session.py
#: and scripts/tunnel_watch.sh (BENCH_NOTES.md "Tunnel discipline")
_TUNNEL_LOCK = "/tmp/tpu_in_use"


def _lock_holder_alive() -> int | None:
    """PID of a LIVE process holding the tunnel lock, else None (no lock,
    unreadable lock, or stale lock from a dead holder)."""
    try:
        with open(_TUNNEL_LOCK) as f:
            pid = int(f.read().strip() or 0)
    except (OSError, ValueError):
        return None
    if pid <= 0 or pid == os.getpid():
        return None
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return None
    except PermissionError:
        pass  # exists but not ours — still alive
    return pid


def _try_acquire_tunnel_lock() -> tuple[bool, int | None]:
    """Atomically take the tunnel lock (O_CREAT|O_EXCL — a check-then-write
    would race another client and clobber its lock).  Returns
    ``(taken, live_holder_pid)``: on EEXIST a live holder is reported, a
    stale lock (dead holder) is removed and the acquire retried once.  A
    filesystem error yields (False, None) — proceed unlocked rather than
    refusing to measure."""
    for _ in range(2):
        try:
            fd = os.open(_TUNNEL_LOCK, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            with os.fdopen(fd, "w") as f:
                f.write(str(os.getpid()))
            return True, None
        except FileExistsError:
            pid = _lock_holder_alive()
            if pid is not None:
                return False, pid
            try:
                os.remove(_TUNNEL_LOCK)
            except OSError:
                return False, None
        except OSError:
            return False, None
    # loop exhausted: another client re-created the lock between our stale
    # removal and the retry.  Report its (live) pid instead of (False, None)
    # — a None holder reads as "filesystem error, proceed unlocked", which
    # would dial a second client into the single-client relay right as the
    # winner starts measuring (ADVICE low).
    return False, _lock_holder_alive()


def _probe_devices() -> str | None:
    """Check the accelerator is reachable.  Returns None when an accelerator
    backend is up, ``_CPU_ONLY`` when jax works but only CPU is visible, else
    a short error string.  Timeouts retry with backoff — the tunnel sometimes
    recovers between attempts; deterministic failures return immediately."""
    last = "device probe never ran"
    for attempt in range(PROBE_ATTEMPTS):
        if attempt:
            time.sleep(PROBE_BACKOFF_SECONDS)
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; jax.devices(); print(jax.default_backend())"],
                capture_output=True,
                text=True,
                timeout=PROBE_TIMEOUT,
            )
            if probe.returncode == 0:
                out_lines = (probe.stdout or "").strip().splitlines()
                backend = out_lines[-1] if out_lines else ""
                return _CPU_ONLY if backend == "cpu" else None
            err_lines = (probe.stderr or "").strip().splitlines()
            # a fast nonzero exit is deterministic (import error, missing
            # backend) — retrying with backoff only helps wedged tunnels
            return err_lines[-1][:200] if err_lines else "device probe failed"
        except subprocess.TimeoutExpired:
            last = (
                f"device probe timed out after {PROBE_TIMEOUT}s "
                f"(attempt {attempt + 1}/{PROBE_ATTEMPTS}; TPU tunnel wedged)"
            )
    return last


def _supervise(argv, preset: str, requested: dict | None = None) -> int:
    """Run the real bench in a subprocess with a watchdog.

    A wedged tunnel hangs *any* process at jax import, so this wrapper never
    imports jax; it guarantees the driver always gets its one JSON line, and
    that the line carries the last verified on-chip number when a fresh
    measurement cannot be taken.
    """
    # the tiny preset is a CPU-safe smoke of a different metric — never
    # substitute the persisted full-ResNet number for it
    run_metric = "cifar10_basicnn_train_throughput" if preset == "tiny" else METRIC
    # a gradient-transport arm trains with lossy gradient exchange: it is
    # a DIFFERENT metric, so keep-best can never promote it to (nor cite
    # it as) the exact-training headline
    if requested and requested.get("comm_dtype"):
        run_metric += f"_comm_{requested['comm_dtype']}"
    # a weight-update-sharded arm (ISSUE 8) trains under a different
    # sharding tier AND collective schedule: its own metric name too
    if requested and requested.get("comm_shard_tier"):
        run_metric += f"_shard_{requested['comm_shard_tier']}"
    # the serve arm (ISSUE 9) measures a different workload entirely
    # (continuous-batching decode tokens/s): its own metric name, with a
    # quant suffix so lossy-weight serving never cites the exact record
    if requested and requested.get("serve"):
        run_metric = _serve_metric_name(preset, requested.get("serve_quant"))
    # Take the single-client tunnel lock BEFORE dialing anything (the probe
    # itself is a client).  A live holder means the measurement session is
    # busy writing the very records this run would cite — emit the
    # persisted number instead of racing it (dialing a second client is
    # the documented wedge trigger).
    lock_taken = False
    if preset != "tiny":
        lock_taken, holder = _try_acquire_tunnel_lock()
        if not lock_taken and holder is not None:
            return _emit_persisted(
                run_metric,
                f"tunnel held by live measurement session (pid {holder}); "
                f"not dialing a second client into the single-client relay",
                requested,
            )
    # the lock is held through probe AND measurement so the background
    # watcher's periodic probe never dials a second client mid-run
    try:
        err = _probe_devices()
        if err == _CPU_ONLY and preset != "tiny":
            # don't burn the watchdog on a CPU ResNet-50 run whose result
            # the on_accelerator check would discard anyway
            return _emit_persisted(
                run_metric,
                "device probe found CPU-only backend (no TPU visible)",
                requested,
            )
        if err is not None and err != _CPU_ONLY:
            return _emit_persisted(run_metric, err, requested)
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--_worker", *argv],
            capture_output=True,
            text=True,
            timeout=WATCHDOG_SECONDS,
        )
        for line in reversed(out.stdout.strip().splitlines()):
            if line.startswith("{"):
                try:
                    parsed = json.loads(line)
                except ValueError:
                    continue  # stray brace-prefixed log line, keep scanning
                if "metric" not in parsed:
                    continue
                if parsed.get("on_accelerator") and parsed.get("value", 0) > 0:
                    # the worker already persisted its own record (single
                    # source of truth for the BENCH_RESULTS.json schema)
                    print(line)
                    return 0
                # Headline measurement ran but on CPU (tunnel handed back no
                # TPU): the persisted on-chip number is the honest headline.
                # (run_metric carries the comm-arm suffix, so a transport
                # arm only ever cites its own metric's record.)
                if (not parsed.get("on_accelerator") and preset != "tiny"
                        and parsed["metric"] == run_metric):
                    return _emit_persisted(
                        parsed["metric"],
                        "bench ran on CPU backend (no accelerator visible)",
                        requested,
                    )
                print(line)
                return 0
        err_lines = (out.stderr or "no JSON output").strip().splitlines()
        detail = err_lines[-1][:200] if err_lines else "unknown"
    except subprocess.TimeoutExpired:
        detail = f"timeout after {WATCHDOG_SECONDS}s (TPU tunnel wedged?)"
    finally:
        if lock_taken:
            try:
                os.remove(_TUNNEL_LOCK)
            except OSError:
                pass
    return _emit_persisted(run_metric, detail, requested)


def _serve_bench(args, tiny: bool) -> int:
    """Serving bench arm (ISSUE 9 satellite): a synthetic Poisson request
    trace through the continuous-batching engine.

    Two passes over the same trace: the first warms every compiled
    prefill bucket + the decode program, the second is the measurement —
    steady-state serving is what the metric claims (compile seconds are
    the AOT ledger's job, not this arm's).  Emits ONE JSON line with
    tokens/s as ``value`` plus the p50/p99 TTFT & TPOT, KV-block
    occupancy, and batch-fill columns, and persists an on-accelerator
    capture to the ledger under its own metric + config keys.
    """
    import numpy as np

    import jax

    from stoke_tpu.configs import (
        AttributionConfig,
        MemoryConfig,
        ServeConfig,
    )
    from stoke_tpu.models.gpt import GPT
    from stoke_tpu.serving import RequestSLO, ServingEngine
    from stoke_tpu.utils import init_module

    on_accel = jax.default_backend() not in ("cpu",)
    metric = _serve_metric_name(args.preset, args.serve_quant)
    size = "tiny" if tiny else "small"
    vocab = 1024 if tiny else 8192
    model = GPT(
        vocab_size=vocab, size_name=size, max_len=512, dropout_rate=0.0
    )
    variables = init_module(
        model, jax.random.PRNGKey(0), np.zeros((1, 8), np.int32), train=False
    )
    # long-prompt arm (ISSUE 13): chunked prefill is the knob under test,
    # so the arm defaults it ON at one pad bucket (32) when unset
    long_arm = bool(args.serve_long_prompt)
    chunk = args.serve_prefill_chunk or (32 if long_arm else None)
    sampling = args.serve_sampling != "greedy"
    # priority-mix arm (ISSUE 16): alternate every submitted request
    # between two SLO classes — "interactive" with tight deadlines (the
    # class the attainment fraction is expected to strain under load) and
    # "batch" with loose ones — and report per-class attainment plus
    # goodput-under-SLO tokens/s beside the raw-throughput headline
    mix = bool(args.serve_priority_mix)
    # speculative arm (ISSUE 17): serve a repetitive-text trace (tiled
    # n-gram motifs — the workload prompt-lookup drafting exists for)
    # through the k-token verify programs, and run the SAME trace through
    # a non-speculative engine as the comparison leg: the headline pair
    # is accepted-tokens-per-dispatch vs the strictly-greater baseline
    # dispatch count at equal emitted tokens
    spec = bool(args.serve_speculative)
    spec_k = 4
    _MIX_SLOS = (
        RequestSLO(priority="interactive",
                   ttft_target_s=0.5, tpot_target_s=0.1),
        RequestSLO(priority="batch",
                   ttft_target_s=10.0, tpot_target_s=1.0),
    )

    def build_engine(chunk_tokens, speculative=False):
        cfg = ServeConfig(
            max_seqs=args.serve_max_seqs,
            kv_block_size=16,
            max_seq_len=256,
            max_new_tokens=32,
            prefill_pad_multiple=32,
            quant=args.serve_quant,
            quant_min_size=256,
            decode_kernel=args.serve_decode_kernel,
            prefill_chunk_tokens=chunk_tokens,
            # the verify program samples its targets, so the speculative
            # arm runs the sampling-aware programs even in greedy mode
            # (temperature 0 keeps the streams argmax-deterministic)
            sampling=sampling or speculative,
            # the topp arm's knobs: a representative production mix
            temperature=0.8 if sampling else 0.0,
            top_p=0.9 if sampling else None,
            speculative_k=spec_k if speculative else None,
            # roofline columns (ISSUE 18) ride every serve arm — the
            # observatory is host-side bookkeeping, so the dispatched
            # programs (and the tokens/s headline) are unchanged
            cost_cards=True,
        )
        attribution = AttributionConfig(
            peak_tflops=V5E_PEAK_TFLOPS, peak_hbm_gbps=V5E_PEAK_HBM_GBPS
        )
        return (
            ServingEngine(
                model, variables["params"], cfg, attribution=attribution,
                # memory arm (ISSUE 19): the engine's own HBM ledger
                # (quantized weight store + KV block pool) plus the
                # per-program memory_analysis peaks and the KV headroom
                # forecast — host-side bookkeeping, programs unchanged
                memory=MemoryConfig() if args.memory else None,
            ),
            cfg,
        )

    eng, cfg = build_engine(chunk, speculative=spec)

    n = args.serve_requests or (8 if tiny else 48)
    r = np.random.default_rng(0)
    if spec:
        # repetitive-text trace: each prompt tiles a short random motif,
        # so both the prompt window and the model's own (cycling) greedy
        # continuation are draftable by the n-gram lookup
        prompts = []
        for _ in range(n):
            motif = r.integers(1, vocab, size=int(r.integers(2, 5)))
            reps = int(r.integers(3, 7))
            prompts.append(np.tile(motif, reps).astype(np.int32))
        out_lens = np.full(n, 24)
        arrivals = np.cumsum(r.exponential(0.02 if tiny else 0.05, size=n))
        long_prompt = None
    elif long_arm:
        # one near-max prompt admitted while short requests decode: the
        # worst-case TPOT-stall scenario chunked prefill exists to fix
        long_len = cfg.max_seq_len - 40
        prompts = [
            r.integers(1, vocab, size=int(L)).astype(np.int32)
            for L in r.integers(8, 33, size=max(n - 1, 2))
        ]
        out_lens = np.full(len(prompts), 24)
        arrivals = np.zeros(len(prompts))
        long_prompt = r.integers(1, vocab, size=long_len).astype(np.int32)
    else:
        prompts = [
            r.integers(1, vocab, size=int(L)).astype(np.int32)
            for L in r.integers(8, 65, size=n)
        ]
        out_lens = r.integers(8, 33, size=n)
        # Poisson arrivals: exponential inter-arrivals at a rate that
        # keeps the queue pressured (continuous batching has work to do)
        arrivals = np.cumsum(r.exponential(0.02 if tiny else 0.05, size=n))
        long_prompt = None

    def _token_count(engine, rid):
        req = engine.scheduler.finished.get(rid)
        if req is not None:
            return len(req.tokens)
        for s in engine.scheduler.slots:
            if s.request is not None and s.request.rid == rid:
                return len(s.request.tokens)
        return 0

    def trace_pass(engine, tag_slo=False):
        """One pass over the trace.  In the long-prompt arm the long
        request admits after the shorts start decoding, and the return
        carries the worst inter-token gap any short request saw — the
        TPOT stall the chunked/unchunked comparison reports.  With
        ``tag_slo`` (the priority-mix arm's MEASURED pass only — the warm
        pass's compile-dominated latencies must not poison attainment)
        every request alternates between the two SLO classes."""
        fills, occs = [], []
        i = 0
        base = time.perf_counter()
        tokens0 = engine.metrics.tokens_out.value
        watch = {}
        stall = 0.0
        long_submitted = not long_arm
        while i < len(prompts) or engine.scheduler.has_work:
            now = time.perf_counter() - base
            while i < len(prompts) and arrivals[i] <= now:
                rid = engine.submit(
                    prompts[i], int(out_lens[i]),
                    slo=_MIX_SLOS[i % 2] if (tag_slo and mix) else None,
                )
                watch[rid] = (0, time.perf_counter())
                i += 1
            if long_arm and not long_submitted and i >= len(prompts):
                # shorts admitted and decoding: drop the long prompt in
                engine.step()
                engine.submit(long_prompt, 8)
                long_submitted = True
            if engine.scheduler.has_work:
                engine.step()
                t_now = time.perf_counter()
                for rid, (cnt, ts) in list(watch.items()):
                    c = _token_count(engine, rid)
                    if c > cnt:
                        if cnt > 0:
                            stall = max(stall, t_now - ts)
                        watch[rid] = (c, t_now)
                fills.append(engine.scheduler.batch_fill)
                occs.append(engine.allocator.occupancy)
            elif i < len(prompts):
                time.sleep(min(max(arrivals[i] - now, 0.0), 0.01))
        dt = time.perf_counter() - base
        return {
            "wall_s": dt,
            "tokens": engine.metrics.tokens_out.value - tokens0,
            "batch_fill_mean": float(np.mean(fills)) if fills else 0.0,
            "kv_occupancy_peak": float(np.max(occs)) if occs else 0.0,
            "tpot_stall_s": stall,
        }

    trace_pass(eng)  # warm pass: compiles every prefill bucket + decode
    # steady-state latency is the claim: drop the warm pass's compile-
    # dominated TTFT/TPOT samples before the measured pass
    eng.metrics.reset_latency_reservoirs()
    d0 = eng.metrics.decode_steps.value
    ds0 = eng.metrics.decode_s.value
    spec0 = (
        (eng.metrics.spec_draft_tokens.value,
         eng.metrics.spec_accepted_tokens.value)
        if spec else (0.0, 0.0)
    )
    measured = trace_pass(eng, tag_slo=True)
    decode_dispatches = eng.metrics.decode_steps.value - d0
    decode_wall_s = eng.metrics.decode_s.value - ds0

    spec_cols = {}
    if spec:
        drafted = eng.metrics.spec_draft_tokens.value - spec0[0]
        accepted = eng.metrics.spec_accepted_tokens.value - spec0[1]
        # the comparison leg: the SAME trace through a non-speculative
        # engine — at equal emitted tokens its dispatch count is the
        # baseline the verify programs are measured against
        eng_off, _ = build_engine(chunk, speculative=False)
        trace_pass(eng_off)  # warm
        b0 = eng_off.metrics.decode_steps.value
        baseline = trace_pass(eng_off)
        base_dispatches = eng_off.metrics.decode_steps.value - b0
        spec_cols = {
            "spec_accept_rate": round(accepted / max(drafted, 1.0), 4),
            "accepted_tokens_per_dispatch": round(
                measured["tokens"] / max(decode_dispatches, 1.0), 4
            ),
            # decode wall seconds per EMITTED token: the per-token latency
            # the verify program buys (the tpot_p* columns describe the
            # same thing per request; this is the fleet-level mean)
            "effective_tpot_s": round(
                decode_wall_s / max(measured["tokens"], 1.0), 6
            ),
            "decode_dispatches": int(decode_dispatches),
            "decode_dispatches_baseline": int(base_dispatches),
            "baseline_tokens": int(baseline["tokens"]),
        }

    slo_cols = {}
    if mix:
        # per-class attainment + goodput-under-SLO (ISSUE 16): tokens of
        # requests that MET their deadlines per wall second — the
        # measuring stick beside the raw tokens/s headline
        by_class = eng.slo.summary().get("by_class", {})
        wall = max(measured["wall_s"], 1e-9)
        slo_cols["slo_goodput_tokens_per_s"] = round(
            eng.slo.goodput_tokens_per_s(), 2
        )
        for cls in ("interactive", "batch"):
            st = by_class.get(cls, {})
            att = st.get("attainment")
            slo_cols[f"slo_attainment_{cls}"] = (
                None if att is None else round(att, 4)
            )
            slo_cols[f"slo_goodput_tokens_per_s_{cls}"] = round(
                st.get("goodput_tokens", 0) / wall, 2
            )

    # roofline columns (ISSUE 18): achieved-vs-attainable at the v5e
    # peaks, from the engine's analytic cost cards
    cost = eng.summary()["cost"]

    def _cost_round(v, nd=6):
        return None if v is None else round(v, nd)

    cost_cols = {
        "serve_mfu": _cost_round(cost.get("mfu")),
        "hbm_bw_util": _cost_round(cost.get("hbm_bw_util")),
        "flops_per_token": _cost_round(cost.get("flops_per_token"), 1),
        "attainable_tpot_s": _cost_round(
            cost.get("attainable_tpot_s"), 9
        ),
    }

    # memory columns (ISSUE 19): the serving engine's analytic resident
    # ledger and the capacity fraction still free after the predicted
    # peak (None off-accelerator — no capacity to fraction against)
    mem_cols = {}
    if args.memory:
        ms = eng.summary()["memory"]
        _cap = ms.get("capacity_bytes")
        _head = ms.get("headroom_bytes")
        mem_cols = {
            "memory": True,
            "mem_resident_bytes": ms.get("resident_bytes"),
            "mem_temp_peak_bytes": ms.get("temp_peak_bytes"),
            "mem_headroom_frac": (
                None if not _cap or _head is None
                else round(_head / _cap, 4)
            ),
        }

    # scrape-under-load guard (ISSUE 20): re-run the SAME trace with a
    # live ops plane attached and a poller hammering /metrics + /statusz
    # the whole pass; the per-emitted-token decode wall time vs the
    # unscraped measured pass above is the scrape tax.  The claim is
    # that GET handlers on a daemon thread never stall the decode loop.
    scrape = bool(args.serve_scrape)
    scrape_cols = {}
    if scrape:
        import threading
        import urllib.request

        from stoke_tpu.configs import OpsPlaneConfig
        from stoke_tpu.telemetry.opsplane import OpsPlane

        tpot_off = decode_wall_s / max(measured["tokens"], 1.0)
        # the headline ttft/tpot percentiles describe the UNSCRAPED
        # measured pass — snapshot them before the re-run refills the
        # reservoirs under poller load
        pct_unscraped = eng.metrics.latency_percentiles()
        eng.metrics.reset_latency_reservoirs()
        plane = OpsPlane(OpsPlaneConfig(port=0))
        plane.attach_engine(eng)
        plane.start()
        stop = threading.Event()
        polls = [0]

        def _poll():
            base = f"http://127.0.0.1:{plane.port}"
            while not stop.is_set():
                for ep in ("/metrics", "/statusz"):
                    try:
                        with urllib.request.urlopen(
                            base + ep, timeout=5
                        ) as r:
                            r.read()
                        polls[0] += 1
                    except Exception:
                        pass  # a torn scrape is the poller's problem

        poller = threading.Thread(target=_poll, daemon=True)
        poller.start()
        ds_on0 = eng.metrics.decode_s.value
        scraped = trace_pass(eng)
        stop.set()
        poller.join(timeout=5.0)
        plane.close()
        tpot_on = (eng.metrics.decode_s.value - ds_on0) / max(
            scraped["tokens"], 1.0
        )
        delta = (tpot_on - tpot_off) / max(tpot_off, 1e-9)
        scrape_cols = {
            "scrape_polls": polls[0],
            "scrape_tpot_delta_frac": round(delta, 4),
            # the always-on-scrape claim: < 5% TPOT tax under a hostile
            # poller (CPU captures are noisy; the on-chip capture is the
            # binding verdict, same discipline as numerics_overhead_ok)
            "scrape_overhead_ok": bool(delta < 0.05),
        }

    stall_unchunked = None
    if long_arm:
        # the comparison leg: same trace, chunking disabled — its stall
        # column is what chunked prefill is measured against
        eng_off, _ = build_engine(None)
        trace_pass(eng_off)  # warm
        stall_unchunked = trace_pass(eng_off)["tpot_stall_s"]
    tokens_per_s = measured["tokens"] / max(measured["wall_s"], 1e-9)
    pct = pct_unscraped if scrape else eng.metrics.latency_percentiles()
    result = {
        "metric": metric,
        "value": round(tokens_per_s, 2),
        "unit": "tokens/sec",
        "vs_baseline": round(
            tokens_per_s / A100_BASELINE_SERVE_TOKENS_PER_SEC, 4
        ),
        "serve": True,
        "serve_quant": args.serve_quant,
        "serve_max_seqs": cfg.max_seqs,
        # serve fast-path columns (ISSUE 13): decode kernel, chunking,
        # and sampling mode are distinct configurations for the
        # regression/substitution guards
        "serve_decode_kernel": args.serve_decode_kernel,
        "serve_prefill_chunk": chunk,
        "serve_sampling": args.serve_sampling,
        "serve_long_prompt": True if long_arm else None,
        "serve_priority_mix": True if mix else None,
        "serve_speculative": True if spec else None,
        "serve_scrape": True if scrape else None,
        **(
            {
                "tpot_stall_chunked_s": round(measured["tpot_stall_s"], 6),
                "tpot_stall_unchunked_s": round(stall_unchunked, 6),
            }
            if long_arm
            else {}
        ),
        **spec_cols,
        **slo_cols,
        **cost_cols,
        **mem_cols,
        **scrape_cols,
        "requests": n,
        "ttft_p50_s": round(pct["ttft_p50_s"], 6),
        "ttft_p99_s": round(pct["ttft_p99_s"], 6),
        "tpot_p50_s": round(pct["tpot_p50_s"], 6),
        "tpot_p99_s": round(pct["tpot_p99_s"], 6),
        "batch_fill_mean": round(measured["batch_fill_mean"], 4),
        "kv_occupancy_peak": round(measured["kv_occupancy_peak"], 4),
        "kv_occupancy_final": eng.allocator.occupancy,
        "quant_compression": round(eng.quant_stats["compression"], 4),
        # per-layer dequant-error attribution (ISSUE 12): which module
        # bounds int8 quality in this capture (None without quantization)
        "quant_err_max": (
            None if eng.quant_err_max is None
            else round(eng.quant_err_max, 6)
        ),
        "quant_err_layer": eng.quant_err_layer,
        "on_accelerator": on_accel,
        "fresh": True,
        "measured_on": time.strftime("%Y-%m-%d"),
    }
    if on_accel:
        regression = check_regression(
            metric, result["value"],
            config={
                "serve": True,
                "serve_quant": args.serve_quant,
                "serve_max_seqs": cfg.max_seqs,
                "serve_decode_kernel": args.serve_decode_kernel,
                "serve_prefill_chunk": chunk,
                "serve_sampling": args.serve_sampling,
                "serve_long_prompt": True if long_arm else None,
                "serve_priority_mix": True if mix else None,
                "serve_speculative": True if spec else None,
                "serve_scrape": True if scrape else None,
                "memory": True if args.memory else None,
            },
        )
        if regression is not None:
            result["regression"] = regression
            print(
                f"bench.py REGRESSION: {metric} fresh {result['value']} is "
                f"{regression['ratio']:.2%} of ledger best "
                f"{regression['best']}",
                file=sys.stderr,
            )
    print(json.dumps(result))
    if on_accel:
        persist_result(
            metric,
            {
                "value": result["value"],
                "unit": result["unit"],
                "vs_baseline": result["vs_baseline"],
                "date": result["measured_on"],
                "source": "bench.py --serve fresh capture",
                "backend": jax.default_backend(),
                "serve": True,
                "serve_quant": args.serve_quant,
                "serve_max_seqs": cfg.max_seqs,
                "serve_decode_kernel": args.serve_decode_kernel,
                "serve_prefill_chunk": chunk,
                "serve_sampling": args.serve_sampling,
                "serve_long_prompt": True if long_arm else None,
                "serve_priority_mix": True if mix else None,
                "serve_speculative": True if spec else None,
                "serve_scrape": True if scrape else None,
                **(
                    {
                        "tpot_stall_chunked_s": result[
                            "tpot_stall_chunked_s"
                        ],
                        "tpot_stall_unchunked_s": result[
                            "tpot_stall_unchunked_s"
                        ],
                    }
                    if long_arm
                    else {}
                ),
                **spec_cols,
                **slo_cols,
                **cost_cols,
                **mem_cols,
                **scrape_cols,
                "requests": n,
                "ttft_p50_s": result["ttft_p50_s"],
                "ttft_p99_s": result["ttft_p99_s"],
                "tpot_p50_s": result["tpot_p50_s"],
                "tpot_p99_s": result["tpot_p99_s"],
                "batch_fill_mean": result["batch_fill_mean"],
                "kv_occupancy_peak": result["kv_occupancy_peak"],
                "quant_compression": result["quant_compression"],
                "quant_err_max": result["quant_err_max"],
                "quant_err_layer": result["quant_err_layer"],
            },
            keep_best=True,
        )
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", choices=["full", "tiny"], default="full",
                    help="tiny = CPU-safe smoke (BasicNN, few steps)")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--warmup", type=int, default=None)
    ap.add_argument("--api", choices=["4call", "train_step", "train_steps"],
                    default="train_steps",
                    help="facade path to measure; train_steps (multi-step "
                    "scan, one dispatch per N optimizer steps) is the "
                    "fastest measured (scripts/bench_sweep.py)")
    ap.add_argument("--seg", type=int, default=None,
                    help="optimizer steps per train_steps dispatch (default "
                    "10) — the per-step share of dispatch/relay round-trip "
                    "latency is RTT/seg (see profile_capture.py seg_sweep). "
                    "Explicitly setting it makes the stale-substitution "
                    "guard strict about it; the default run accepts the "
                    "best-known record at ANY segment length (it is a "
                    "tuning knob of the same metric, and keep-best may "
                    "legitimately have promoted a seg-50 record)")
    ap.add_argument("--comm-dtype", default=None,
                    choices=["fp32", "bf16", "int8"],
                    help="A/B arm for the gradient-transport layer "
                    "(CommConfig): wire dtype of the gradient exchange.  "
                    "On one chip this measures the quantize/dequantize "
                    "overhead (the collective itself is a no-op at world "
                    "size 1); on a pod it measures the bytes-on-wire win.  "
                    "A distinct configuration for the stale-substitution "
                    "and regression guards")
    ap.add_argument("--comm-shard-tier", default=None,
                    choices=["none", "oss", "sddp", "fsdp"],
                    help="run the --comm-dtype arm under a sharding tier "
                    "(ISSUE 8 weight-update sharding): quantized "
                    "reduce-scatter of the gradient leg, shard-local "
                    "optimizer step over the partitioned state, "
                    "updated-param all-gather.  The result records the "
                    "tier plus grad/param bytes-on-wire and compression "
                    "columns.  'none' is the explicit replicated "
                    "baseline.  Requires --comm-dtype; a distinct "
                    "configuration for the stale-substitution and "
                    "regression guards")
    ap.add_argument("--xla-flags", default="",
                    help="extra XLA_FLAGS for the measurement (A/B autotune "
                    "arms); applied in the worker BEFORE jax import.  An "
                    "explicitly-flagged request is a distinct configuration "
                    "(a record with different flags is never substituted "
                    "for it); a default request accepts the best verified "
                    "record whatever its flags — flags are a tuning knob "
                    "of the same metric")
    ap.add_argument("--health", action="store_true",
                    help="enable the training health monitor (ISSUE 3): "
                    "on-device sentinels + anomaly detectors ride the "
                    "measured run and the capture's ledger descriptor "
                    "records the anomaly counts.  Sentinels fetch a tiny "
                    "vector per step (one host sync), so a --health "
                    "capture is a distinct configuration for the "
                    "stale-substitution guard")
    ap.add_argument("--attribution-peak-tflops", type=float, default=None,
                    help="enable step-time attribution (ISSUE 4) on the "
                    "measured run with this peak TFLOP/s as the MFU "
                    "denominator (measure it with scripts/flops_probe.py's "
                    "matmul-peak probe; v5e bf16 dense: 197).  The result "
                    "and ledger descriptor gain mfu / achieved_tflops / "
                    "goodput columns.  Attribution is host-side bookkeeping "
                    "plus one cost-analysis per compiled program, but still "
                    "a distinct configuration for the stale-substitution "
                    "guard")
    ap.add_argument("--fleet", action="store_true",
                    help="enable fleet observability (ISSUE 5) on the "
                    "measured run: per-window packed-signal exchange, "
                    "cross-host skew aggregation, barrier-wait "
                    "attribution.  On one chip the fleet is one host and "
                    "this measures the monitor's own overhead; on a pod "
                    "the ledger descriptor records the skew columns.  A "
                    "distinct configuration for the stale-substitution "
                    "and regression guards")
    ap.add_argument("--tuned", action="store_true",
                    help="replay the autotune ledger winner (ISSUE 6): "
                    "apply its xla_flags/batch/steps_per_dispatch "
                    "(explicit --batch/--seg still win) and run with the "
                    "persistent AOT compile cache enabled so warm starts "
                    "reclaim compile seconds.  The capture's ledger "
                    "descriptor records tuned/cache_hit columns — a "
                    "distinct configuration for the stale-substitution "
                    "and regression guards")
    ap.add_argument("--trace", action="store_true",
                    help="structured-tracing overhead arm (ISSUE 10): run "
                    "the measured loop with a TraceConfig span ring "
                    "recording every dispatch/phase, then re-measure with "
                    "the recorder unplugged, and report the column "
                    "trace_overhead_frac = (on - off)/off.  The always-on "
                    "tracing claim is that this stays < 1%; "
                    "trace_overhead_ok records the verdict.  A distinct "
                    "configuration for the stale-substitution and "
                    "regression guards")
    ap.add_argument("--numerics", action="store_true",
                    help="per-layer numerics arm (ISSUE 12): the measured "
                    "run computes the per-module group-stats matrix "
                    "inside every step program and fetches it per "
                    "boundary; an off-control facade (same compiled "
                    "APIs, NumericsConfig dropped) is measured in "
                    "interleaved adjacent pairs (the PR-10 discipline — "
                    "sequential arms drown a sub-2%% signal in warm-up "
                    "drift) and numerics_overhead_frac / "
                    "numerics_overhead_ok (< 2%%) record the verdict.  A "
                    "distinct configuration for the stale-substitution "
                    "and regression guards")
    ap.add_argument("--memory", action="store_true",
                    help="HBM capacity-ledger arm (ISSUE 19): the "
                    "measured run carries the analytic per-subsystem "
                    "memory observatory — params/optimizer/transport/"
                    "snapshot resident ledger, per-program "
                    "memory_analysis peaks, OOM pre-flight — and the "
                    "capture records mem_resident_bytes / "
                    "mem_temp_peak_bytes / mem_headroom_frac columns; "
                    "with --serve the engine's ledger (quantized weight "
                    "store + KV block pool) and headroom forecast ride "
                    "the serve capture instead.  Host-side arithmetic "
                    "plus one memory_analysis compile per program "
                    "signature; the dispatched programs are unchanged.  "
                    "A distinct configuration for the stale-substitution "
                    "and regression guards")
    ap.add_argument("--resilience", action="store_true",
                    help="enable pod-scale resilience (ISSUE 7) on the "
                    "measured run: preemption signal handlers, per-save "
                    "integrity manifests, and the resilience/* counters.  "
                    "No preemption fires during a bench, so this measures "
                    "the subsystem's overhead (manifest digests per save; "
                    "zero per-step work) and records the "
                    "restarts/resumed_step/lost_steps columns in the "
                    "ledger descriptor.  A distinct configuration for the "
                    "stale-substitution and regression guards")
    ap.add_argument("--serve", action="store_true",
                    help="serving bench arm (ISSUE 9): a synthetic request "
                    "trace (Poisson arrivals, mixed prompt/output lengths) "
                    "through the continuous-batching engine — paged "
                    "KV-cache, prefill/decode split, greedy decode.  "
                    "Measures generated tokens/s and records p50/p99 "
                    "TTFT & TPOT, kv_block_occupancy, and batch-fill "
                    "columns.  Its own metric (never substituted for the "
                    "training headline); model size follows --preset "
                    "(tiny -> GPT-tiny, full -> GPT-small)")
    ap.add_argument("--serve-quant", default="none",
                    choices=["none", "bf16", "int8"],
                    help="weight quantization for the --serve arm "
                    "(ServeConfig.quant; int8 reuses the PR-2 per-chunk "
                    "stochastic-rounding wire format on the weights).  A "
                    "lossy-weight capture is a distinct metric for the "
                    "stale-substitution and regression guards")
    ap.add_argument("--serve-max-seqs", type=int, default=8,
                    help="decode slot count of the --serve arm (the "
                    "continuous-batching batch size); a distinct "
                    "configuration for the regression guard")
    ap.add_argument("--serve-requests", type=int, default=None,
                    help="requests in the synthetic trace (default: 8 "
                    "tiny / 48 full)")
    ap.add_argument("--serve-decode-kernel", default="reference",
                    choices=["reference", "pallas"],
                    help="decode attention kernel of the --serve arm "
                    "(ISSUE 13): 'reference' is the jnp gathered-block "
                    "math, 'pallas' the dedicated streaming kernel "
                    "(HBM→VMEM block walk; interpreter parity mode "
                    "off-TPU).  A distinct configuration for the "
                    "stale-substitution and regression guards")
    ap.add_argument("--serve-prefill-chunk", type=int, default=None,
                    help="chunked prefill for the --serve arm "
                    "(ServeConfig.prefill_chunk_tokens; must be a "
                    "multiple of the arm's pad bucket, 32).  Bounds "
                    "per-iteration prefill work so a long prompt cannot "
                    "stall in-flight TPOT.  A distinct configuration for "
                    "the guards")
    ap.add_argument("--serve-sampling", default="greedy",
                    choices=["greedy", "topp"],
                    help="sampling mode of the --serve arm: 'greedy' is "
                    "the deterministic argmax baseline, 'topp' serves "
                    "temperature 0.8 / top-p 0.9 through the sampling-"
                    "aware programs (per-request seeded key streams).  A "
                    "distinct configuration for the guards")
    ap.add_argument("--serve-long-prompt", action="store_true",
                    help="long-prompt arm (ISSUE 13): one near-max "
                    "prompt admitted while short requests decode; "
                    "reports the worst-case TPOT stall the in-flight "
                    "requests saw WITH chunked prefill "
                    "(tpot_stall_chunked_s; chunking defaults on at one "
                    "pad bucket) and WITHOUT (tpot_stall_unchunked_s) — "
                    "the column pair that shows what chunking buys.  A "
                    "distinct configuration for the guards")
    ap.add_argument("--serve-priority-mix", action="store_true",
                    help="priority-mix arm (ISSUE 16): every request in "
                    "the Poisson trace carries a RequestSLO, alternating "
                    "between an 'interactive' class (tight TTFT/TPOT "
                    "deadlines) and a 'batch' class (loose ones); reports "
                    "per-class SLO attainment fractions and "
                    "goodput-under-SLO tokens/s (tokens of requests that "
                    "met their deadlines) beside the raw throughput "
                    "headline.  A distinct configuration for the "
                    "stale-substitution and regression guards")
    ap.add_argument("--serve-speculative", action="store_true",
                    help="speculative-decoding arm (ISSUE 17): serve a "
                    "repetitive-text trace (tiled n-gram motifs) through "
                    "the self-drafting verify programs (prompt-lookup "
                    "drafter, k-token verify dispatch, k=4) and the same "
                    "trace through a non-speculative engine as the "
                    "comparison leg.  Reports spec_accept_rate, "
                    "accepted_tokens_per_dispatch, effective_tpot_s, and "
                    "the decode_dispatches / decode_dispatches_baseline "
                    "pair (fewer dispatches at equal emitted tokens is "
                    "what speculation buys).  A distinct configuration "
                    "for the stale-substitution and regression guards")
    ap.add_argument("--serve-scrape", action="store_true",
                    help="scrape-under-load arm (ISSUE 20): after the "
                    "unscraped measured pass, re-run the same trace with "
                    "a live ops plane bound on an ephemeral loopback port "
                    "and a poller hammering /metrics + /statusz the whole "
                    "pass; reports scrape_polls, scrape_tpot_delta_frac "
                    "(per-emitted-token decode wall time vs the unscraped "
                    "pass), and the scrape_overhead_ok (< 5%%) verdict.  "
                    "The headline value and latency percentiles still "
                    "describe the UNSCRAPED pass.  A distinct "
                    "configuration for the stale-substitution and "
                    "regression guards")
    ap.add_argument("--_worker", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()
    tuned_rec = None
    if args.tuned:
        # preset-aware lookup (same preset -> metric rule _supervise
        # uses): the tiny preset replays the smoke winner, never the
        # ResNet one — a winner's knobs only make sense for the workload
        # they were measured on
        tuned_metric = (
            "cifar10_basicnn_train_throughput"
            if args.preset == "tiny" else METRIC
        )
        if args.comm_shard_tier:
            # a tier sweep persists its winner under a tier-suffixed
            # metric (scripts/autotune.py): the requested tier selects
            # WHICH winner to replay, and that winner's knobs (comm_dtype
            # included) become the run defaults below
            tuned_metric += f"_shard_{args.comm_shard_tier}"
        tuned_rec = _load_results().get(f"autotune/{tuned_metric}")
        if tuned_rec is None:
            print(json.dumps({
                "metric": tuned_metric,
                "value": 0.0,
                "error": "--tuned requested but no autotune winner is "
                "persisted for this preset's metric; run "
                "scripts/autotune.py first",
            }))
            sys.exit(1)
        spec = tuned_rec.get("spec") or {}
        # winner knobs become the run defaults (explicit flags still win)
        if not args.xla_flags and spec.get("xla_flags"):
            args.xla_flags = spec["xla_flags"]
        if args.batch is None and spec.get("batch"):
            args.batch = int(spec["batch"])
        if args.seg is None and spec.get("steps_per_dispatch"):
            args.seg = int(spec["steps_per_dispatch"])
        if args.comm_dtype is None and spec.get("comm_dtype"):
            args.comm_dtype = spec["comm_dtype"]
    if args.comm_shard_tier and not args.comm_dtype:
        ap.error("--comm-shard-tier requires --comm-dtype (the tier arm "
                 "measures the sharded transport's wire format; with "
                 "--tuned the tier winner's swept dtype satisfies this)")
    if not args._worker:
        # XLA_FLAGS must be in the WORKER's environment at interpreter
        # start: flags are fixed at backend init, and the ambient
        # sitecustomize can import jax before worker code runs.  Setting
        # them here (the parent never imports jax) is the only reliable
        # path — the worker's own env mutation (the old bench.py:500)
        # silently failed whenever jax beat it to the import.
        missing = _missing_flag_tokens(
            args.xla_flags, os.environ.get("XLA_FLAGS", "")
        )
        if missing:
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + " " + " ".join(missing)
            ).strip()
        sys.exit(_supervise(
            sys.argv[1:], args.preset,
            requested={
                "serve": True if args.serve else None,
                "serve_quant": (
                    args.serve_quant
                    if args.serve and args.serve_quant != "none"
                    else None
                ),
                "serve_max_seqs": (
                    args.serve_max_seqs if args.serve else None
                ),
                # kernel / sampling / long-prompt wants are ALWAYS
                # explicit for a serve run (defaults included): absent
                # ledger keys normalize to the pre-ISSUE-13 defaults
                # (_SERVE_KEY_DEFAULTS), so a default greedy/reference
                # run never cites a pallas or topp capture and vice
                # versa.  prefill_chunk stays a tuning knob of the same
                # Poisson workload (the --seg rule): explicit = strict,
                # default = any verified chunking
                "serve_decode_kernel": (
                    args.serve_decode_kernel if args.serve else None
                ),
                "serve_prefill_chunk": (
                    args.serve_prefill_chunk if args.serve else None
                ),
                "serve_sampling": (
                    args.serve_sampling if args.serve else None
                ),
                "serve_long_prompt": (
                    bool(args.serve_long_prompt) if args.serve else None
                ),
                "serve_priority_mix": (
                    bool(args.serve_priority_mix) if args.serve else None
                ),
                "serve_speculative": (
                    bool(args.serve_speculative) if args.serve else None
                ),
                "tuned": True if args.tuned else None,
                "fleet": True if args.fleet else None,
                "health": True if args.health else None,
                "resilience": True if args.resilience else None,
                "trace": True if args.trace else None,
                "numerics": True if args.numerics else None,
                # memory wants are ALWAYS explicit (the _SERVE_KEY_DEFAULTS
                # rule, applied to a train+serve key): absent ledger keys
                # normalize to False, so a default run never cites a
                # --memory capture and vice versa
                "memory": bool(args.memory),
                "attribution": (
                    True if args.attribution_peak_tflops else None
                ),
                "api": args.api,
                "batch": args.batch,
                # explicit --seg N: a record at a different segment length
                # is a different configuration — never substituted.  Default
                # (--seg omitted): any verified segment length qualifies.
                "steps_per_dispatch": (
                    max(1, args.seg)
                    if args.seg is not None and args.api == "train_steps"
                    else None
                ),
                # None = unconstrained (default run cites the best record
                # whatever its flags); explicit flags must match exactly
                "xla_flags": args.xla_flags or None,
                # an explicit transport arm is its own configuration; the
                # default (no transport) accepts any record without one
                "comm_dtype": args.comm_dtype,
                "comm_shard_tier": args.comm_shard_tier,
            },
        ))

    missing_flags = _missing_flag_tokens(
        args.xla_flags, os.environ.get("XLA_FLAGS", "")
    )
    if missing_flags:
        # the supervisor already exported the flags into this worker's
        # start environment; reaching here means bench ran worker-direct
        # (scripts/tpu_session.py) or someone stripped the env.  Setting
        # XLA_FLAGS now only works if jax has NOT been imported yet —
        # after import the backend config is frozen and the flags would
        # silently not apply (the old bench.py:500 bug).  Warn LOUDLY in
        # that case instead of emitting a mislabeled measurement.
        if "jax" in sys.modules:
            print(
                f"bench.py WARNING: --xla-flags {args.xla_flags!r} "
                f"requested but jax is already imported in this process; "
                f"the flags will NOT apply to this measurement. Re-exec "
                f"through the bench supervisor (drop --_worker) or export "
                f"XLA_FLAGS before the interpreter starts.",
                file=sys.stderr, flush=True,
            )
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + " ".join(missing_flags)
        ).strip()

    import numpy as np

    import jax
    import optax

    from stoke_tpu import CommConfig, Stoke, StokeOptimizer
    from stoke_tpu.models import BasicNN, ResNet50

    tiny = args.preset == "tiny"
    if args.serve:
        sys.exit(_serve_bench(args, tiny))
    # comm arms carry their own metric name (lossy-gradient training is a
    # distinct configuration, never the exact-training headline); a
    # weight-update-sharded tier (ISSUE 8) extends the name again
    comm_suffix = f"_comm_{args.comm_dtype}" if args.comm_dtype else ""
    if args.comm_shard_tier:
        comm_suffix += f"_shard_{args.comm_shard_tier}"
    on_accel = jax.default_backend() not in ("cpu",)
    batch = args.batch or (16 if tiny else 256)
    steps = args.steps or (3 if tiny else 30)
    warmup = args.warmup if args.warmup is not None else (1 if tiny else 5)

    if tiny:
        model = BasicNN()
    else:
        model = ResNet50(num_classes=10, cifar_stem=True)
    from stoke_tpu.utils import init_module

    variables = init_module(
        model, jax.random.PRNGKey(0), np.zeros((2, 32, 32, 3), np.float32), train=False
    )
    # host copy for the --numerics off-control facade: the engine DONATES
    # its state buffers, so by the time the control is constructed the
    # original init arrays may already be deleted
    off_variables = (
        jax.tree_util.tree_map(np.asarray, variables)
        if args.numerics else None
    )
    # same hazard for the --resilience elastic-resume probe's half-mesh
    # facade (ISSUE 14): it constructs AFTER the measured run donated the
    # init arrays
    elastic_variables = (
        jax.tree_util.tree_map(np.asarray, variables)
        if args.resilience else None
    )
    run_configs = []
    shard_tier = args.comm_shard_tier
    if args.comm_dtype:
        # oss keeps a replicated grad buffer, so shard_updates' auto
        # default resolves REPLICATED there — the tier arm must opt in
        # explicitly or its ledger rows would mislabel the replicated
        # exchange as the sharded path (sddp/fsdp auto-engage)
        run_configs.append(CommConfig(
            dtype=args.comm_dtype,
            shard_updates=True if shard_tier == "oss" else None,
        ))
    if (args.health or args.attribution_peak_tflops or args.fleet
            or args.numerics or args.memory):
        # health (ISSUE 3) / attribution (ISSUE 4) / fleet (ISSUE 5) arms
        # all ride the telemetry pipeline (status-validated requirement)
        # — JSONL only, quiet cadence, no device-time sampling, so the
        # monitor itself is the only perturbation being measured.
        import tempfile

        from stoke_tpu import TelemetryConfig

        obs_dir = tempfile.mkdtemp(prefix="stoke-bench-obs-")
        run_configs.append(TelemetryConfig(
            output_dir=obs_dir, log_every_n_steps=10,
            prometheus=False, tensorboard=False, sample_device_time=False,
        ))
    if args.health:
        from stoke_tpu import HealthConfig

        run_configs.append(HealthConfig(dump_signals=False))
    if args.numerics:
        # numerics arm (ISSUE 12): the per-module group-stats matrix is
        # computed inside every step program of the measured run; the
        # off-control pair below isolates its cost
        from stoke_tpu import NumericsConfig

        run_configs.append(NumericsConfig())
    if args.memory:
        # memory arm (ISSUE 19): the analytic HBM ledger + per-program
        # memory_analysis peaks observe the measured run — host-side
        # arithmetic over trees the run already holds; the step programs
        # themselves are untouched
        from stoke_tpu import MemoryConfig

        run_configs.append(MemoryConfig())
    if args.attribution_peak_tflops:
        # attribution arm (ISSUE 4): CostCards + live MFU + goodput
        # ledger observe the measured run; the ledger descriptor records
        # the MFU/goodput columns.
        from stoke_tpu import AttributionConfig

        run_configs.append(AttributionConfig(
            peak_tflops=args.attribution_peak_tflops,
        ))
    if args.fleet:
        # fleet arm (ISSUE 5): one packed-signal exchange per logged
        # window; the ledger descriptor records the skew columns (on a
        # single chip the fleet is one host and every skew is zero — the
        # arm then measures the monitor's own overhead)
        from stoke_tpu import FleetConfig

        run_configs.append(FleetConfig(window_steps=10))
    if args.trace:
        # tracing arm (ISSUE 10): the span ring records every dispatch
        # and facade phase of the measured run; export is skipped so the
        # arm measures pure record-path overhead, not an exit-time write
        import tempfile

        from stoke_tpu import TraceConfig

        run_configs.append(TraceConfig(
            output_dir=tempfile.mkdtemp(prefix="stoke-bench-trace-"),
            export_on_close=False,
        ))
    if args.resilience:
        # resilience arm (ISSUE 7): signal handlers + per-save manifests
        # + resilience/* counters ride the measured run.  Nothing
        # preempts a bench, so the columns record a quiet subsystem —
        # the arm proves its overhead is negligible and keeps the ledger
        # schema exercised for the chaos-driven runs that DO restart.
        import tempfile

        from stoke_tpu import ResilienceConfig

        run_configs.append(ResilienceConfig(
            save_path=tempfile.mkdtemp(prefix="stoke-bench-resilience-"),
        ))
    if args.tuned:
        # tuned arm (ISSUE 6): replay the autotune winner with the
        # persistent compile cache enabled — a warm start's backend
        # compiles load from the XLA disk cache instead of re-running
        # codegen (step programs still dispatch through plain jax.jit),
        # and the capture records the hit/miss counts alongside the
        # winner's config key
        from stoke_tpu import CompileConfig

        run_configs.append(CompileConfig(
            cache_dir=os.path.join(_REPO, "artifacts", "compile_cache"),
        ))
    def _build_stoke(params_in, cfgs):
        """ONE construction shared by the measured facade and the
        --numerics off-control: the two arms of the interleaved overhead
        pair must differ in their config list ONLY, or the comparison
        silently measures two different configurations."""
        return Stoke(
            model=model,
            optimizer=StokeOptimizer(
                optimizer=optax.sgd,
                optimizer_kwargs={"learning_rate": 0.05, "momentum": 0.9},
            ),
            loss=lambda logits, labels:
                optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels
                ).mean(),
            params=params_in,
            batch_size_per_device=batch,
            device="tpu" if on_accel else "cpu",
            # the transport needs the distributed engine (status rule); on
            # one chip the mesh is 1-wide and the arm measures quantize
            # overhead
            distributed="dp" if args.comm_dtype else None,
            # ISSUE 8 tier arm: the sharded weight-update path engages
            # automatically under sddp/fsdp (CommConfig.shard_updates auto)
            oss=shard_tier in ("oss", "sddp"),
            sddp=shard_tier == "sddp",
            fsdp=shard_tier == "fsdp",
            precision=None if tiny else "bf16",
            configs=cfgs or None,
            model_train_kwargs={"train": True},
            model_eval_kwargs={"train": False},
            verbose=False,
        )

    stoke = _build_stoke(variables, run_configs)

    # Pre-place a rotating pool of device batches: this measures the training
    # step itself (host->HBM transfer overlap is the DataLoader's job and the
    # tunnel used in CI makes per-step device_put non-representative).
    r = np.random.default_rng(0)
    api = args.api
    per_call = 1
    if api == "train_steps":
        # multi-step scan: SEG optimizer steps per compiled dispatch
        SEG = max(1, args.seg or 10)
        xs = jax.device_put(r.normal(size=(SEG, batch, 32, 32, 3)).astype(np.float32))
        ys = jax.device_put(r.integers(0, 10, size=(SEG, batch)))
        per_call = SEG
        steps = max(3, steps // SEG)
        warmup = min(warmup, 1)  # each warmup call is already SEG steps
        pool = None
    else:
        xs = ys = None
        pool = [
            (
                jax.device_put(r.normal(size=(batch, 32, 32, 3)).astype(np.float32)),
                jax.device_put(r.integers(0, 10, size=(batch,))),
            )
            for _ in range(4)
        ]

    def _make_step(facade):
        """ONE step driver shared by the measured facade and the
        --numerics off-control — both arms must run the SAME api path
        over the SAME pre-placed batch pool, or the interleaved pair
        compares two different step programs."""
        if api == "train_steps":
            return lambda i: facade.train_steps(xs, (ys,))

        def step_fn(i):
            x, y = pool[i % len(pool)]
            if api == "train_step":
                return facade.train_step(x, (y,))
            out = facade.model(x)
            loss = facade.loss(out, y)
            facade.backward(loss)
            facade.step()
            return loss

        return step_fn

    def _make_timed(step_fn):
        def timed_fn(n):
            """Wall time for n steps with a forced device fetch at the
            end (block_until_ready is unreliable through remote-device
            tunnels)."""
            t0 = time.perf_counter()
            last = None
            for i in range(n):
                last = step_fn(i)
            np.asarray(jax.tree_util.tree_leaves(last)[0])  # real sync
            return time.perf_counter() - t0

        return timed_fn

    one_step = _make_step(stoke)
    timed = _make_timed(one_step)

    for i in range(warmup):
        one_step(i)
    timed(1)
    # delta timing: (t(2n) - t(n)) / n cancels fixed sync/tunnel overhead
    t1 = timed(steps)
    t2 = timed(2 * steps)
    dt = max(t2 - t1, 1e-9)

    numerics_overhead_frac = None
    if args.numerics:
        # numerics-off control: a SECOND facade with identical model /
        # optimizer / tier / step API whose programs simply omit the
        # group-stats matrix (NumericsConfig dropped; its TelemetryConfig
        # gets its own sink dir so the two JSONL streams never collide).
        # Unlike tracing, the matrix is compiled INTO the program, so the
        # control must be a separate compiled facade — but the interleaved
        # adjacent-pair discipline (ISSUE 10) is the same: drift hits both
        # sides of a pair equally, first pair discarded, median reported.
        # The headline dt above stays untouched.
        import tempfile

        from stoke_tpu import NumericsConfig, TelemetryConfig

        off_configs = [
            TelemetryConfig(
                output_dir=tempfile.mkdtemp(prefix="stoke-bench-numoff-"),
                log_every_n_steps=10, prometheus=False, tensorboard=False,
                sample_device_time=False,
            )
            if isinstance(c, TelemetryConfig)
            else c
            for c in run_configs
            if not isinstance(c, NumericsConfig)
        ]
        stoke_off = _build_stoke(off_variables, off_configs)
        off_step = _make_step(stoke_off)
        timed_off = _make_timed(off_step)

        for i in range(max(warmup, 1)):
            off_step(i)
        timed_off(1)
        timed(steps)  # settle before the paired windows
        fracs = []
        for i in range(7):
            if i % 2 == 0:
                d_on = timed(steps)
                d_off = timed_off(steps)
            else:
                d_off = timed_off(steps)
                d_on = timed(steps)
            fracs.append((d_on - d_off) / d_off)
        fracs = sorted(fracs[1:])  # discard the warm-up pair
        mid = len(fracs) // 2
        numerics_overhead_frac = max(0.0, (fracs[mid - 1] + fracs[mid]) / 2)
        stoke_off.close_telemetry()

    trace_overhead_frac = None
    if args.trace:
        # tracing-off control: SAME facade, SAME compiled programs, SAME
        # input pools — only the span recorder is unplugged, so the pair
        # difference is the record path itself.  Sequential arms drown a
        # sub-1% signal in warm-up drift (the loop keeps speeding up for
        # several windows), so the arms are measured as ADJACENT
        # alternating pairs — drift hits both sides of a pair equally —
        # with the first pair discarded (warm-up, the fleet-view
        # discipline) and the median of per-pair fractions reported.
        # The headline dt above stays untouched.
        from stoke_tpu.telemetry.tracing import (
            register_recorder,
            unregister_recorder,
        )

        def _timed_off(n):
            unregister_recorder(stoke.tracer)
            try:
                return timed(n)
            finally:
                register_recorder(stoke.tracer)

        timed(steps)  # settle before the paired windows
        fracs = []
        for i in range(7):
            if i % 2 == 0:
                d_on = timed(steps)
                d_off = _timed_off(steps)
            else:
                d_off = _timed_off(steps)
                d_on = timed(steps)
            fracs.append((d_on - d_off) / d_off)
        fracs = sorted(fracs[1:])  # discard the warm-up pair
        mid = len(fracs) // 2
        median_frac = (fracs[mid - 1] + fracs[mid]) / 2  # even count
        trace_overhead_frac = max(0.0, median_frac)

    imgs_per_sec = batch * steps * per_call / dt
    result = {
        "metric": (
            METRIC if not tiny else "cifar10_basicnn_train_throughput"
        ) + comm_suffix,
        "value": round(imgs_per_sec, 1),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(imgs_per_sec / A100_BASELINE_IMGS_PER_SEC, 4),
        "api": api,
        "batch": batch,
        "steps_per_dispatch": per_call,
        "on_accelerator": on_accel,
        "fresh": True,
        "measured_on": time.strftime("%Y-%m-%d"),
    }
    if args.xla_flags:
        result["xla_flags"] = args.xla_flags
    if args.comm_dtype:
        result["comm_dtype"] = args.comm_dtype
        # analytic wire accounting of the measured configuration (ISSUE 8
        # columns): grad leg pre-quant vs on-wire, the param all-gather
        # leg under the sharded tiers, and the grad compression ratio
        cb = stoke.comm_bytes or {}
        result["comm_grad_bytes_prequant"] = cb.get("prequant")
        result["comm_grad_bytes_onwire"] = cb.get("onwire")
        result["comm_bytes_param_gather"] = cb.get("param_gather")
        result["comm_compression"] = (
            round(cb["prequant"] / cb["onwire"], 4)
            if cb.get("onwire") else None
        )
    if shard_tier:
        result["comm_shard_tier"] = shard_tier
    if args.health:
        h = stoke.health
        result["health"] = True
        result["health_anomalies"] = h.anomaly_count
        result["health_by_detector"] = h.anomaly_counts_by_detector()
        result["health_bundles"] = len(h.recorder.dumps)
    if args.attribution_peak_tflops:
        # MFU/goodput columns (ISSUE 4): aggregate utilization of the
        # measured run against the supplied peak, plus the goodput
        # partition of its wall clock
        g = stoke.goodput or {}
        result["attribution"] = True
        result["peak_tflops"] = args.attribution_peak_tflops
        result["mfu"] = (
            None if g.get("mfu") is None else round(g["mfu"], 6)
        )
        result["achieved_tflops"] = (
            None if g.get("achieved_tflops") is None
            else round(g["achieved_tflops"], 4)
        )
        result["goodput_fraction"] = (
            None if g.get("goodput_fraction") is None
            else round(g["goodput_fraction"], 4)
        )
        result["goodput_s"] = {
            b: round(g.get(f"{b}_s", 0.0), 3)
            for b in ("productive", "compile", "recompile", "loader",
                      "checkpoint", "halt")
        }
    if args.fleet:
        # skew columns (ISSUE 5): the fleet view of the measured run —
        # window count, hosts, worst per-host lag, straggler verdicts
        f = stoke.fleet_summary or {}
        verdict = f.get("last_verdict") or {}
        result["fleet"] = True
        result["fleet_hosts"] = f.get("n_processes")
        result["fleet_windows"] = f.get("windows")
        result["fleet_straggler_windows"] = f.get("straggler_windows")
        result["fleet_straggler_anomalies"] = f.get("straggler_anomalies")
        result["fleet_last_lag_frac"] = (
            None if verdict.get("lag_frac") is None
            else round(verdict["lag_frac"], 4)
        )
        result["fleet_last_skew_class"] = verdict.get("skew_class")
        result["fleet_barrier_wait_s"] = (
            None if verdict.get("barrier_wait_s") is None
            else round(verdict["barrier_wait_s"], 4)
        )
    if args.trace:
        # tracing columns (ISSUE 10): the overhead verdict of the
        # always-on span ring against the unplugged control, plus the
        # measured run's critical path as the ledger descriptor
        ts = stoke.trace_summary or {}
        result["trace"] = True
        result["trace_overhead_frac"] = round(trace_overhead_frac, 6)
        result["trace_overhead_ok"] = trace_overhead_frac < 0.01
        result["trace_spans"] = ts.get("spans")
        result["trace_dropped"] = ts.get("dropped")
        result["trace_critical_path"] = [
            {"name": c["name"], "self_s": round(c["self_s"], 4)}
            for c in ts.get("critical_path", [])[:3]
        ]
        if not result["trace_overhead_ok"]:
            print(
                f"bench.py TRACE OVERHEAD: tracing-on arm ran "
                f"{trace_overhead_frac:.2%} slower than tracing-off "
                f"(claim is < 1%)",
                file=sys.stderr,
            )
    if args.numerics:
        # numerics columns (ISSUE 12): the per-layer observatory's cost
        # verdict against the off-control, plus which layers the measured
        # run ranked noisiest — the ledger's "where would I bisect first"
        ns = stoke.numerics_summary or {}
        result["numerics"] = True
        result["numerics_groups"] = len(ns.get("groups") or [])
        result["numerics_overhead_frac"] = round(numerics_overhead_frac, 6)
        result["numerics_overhead_ok"] = numerics_overhead_frac < 0.02
        result["numerics_top_noise"] = [
            {"group": t["group"], "noise": round(t["noise"], 6)}
            for t in (ns.get("top_grad_noise") or [])[:3]
        ]
        result["numerics_provenance_events"] = len(
            ns.get("provenance_events") or []
        )
        if not result["numerics_overhead_ok"]:
            print(
                f"bench.py NUMERICS OVERHEAD: numerics-on arm ran "
                f"{numerics_overhead_frac:.2%} slower than numerics-off "
                f"(claim is < 2%)",
                file=sys.stderr,
            )
    if args.memory:
        # memory columns (ISSUE 19): what this capture kept resident,
        # the worst program transient, and the capacity fraction still
        # free after the predicted peak (None off-accelerator — the CPU
        # simulator reports no capacity)
        ms = stoke.memory_summary or {}
        _cap = ms.get("capacity_bytes")
        _head = ms.get("headroom_bytes")
        result["memory"] = True
        result["mem_resident_bytes"] = ms.get("resident_bytes")
        result["mem_temp_peak_bytes"] = ms.get("temp_peak_bytes")
        result["mem_headroom_frac"] = (
            None if not _cap or _head is None else round(_head / _cap, 4)
        )
    if args.resilience:
        # resilience columns (ISSUE 7): the restart/resume accounting of
        # the measured run — quiet here (nothing preempts a bench), but
        # the same columns a chaos-driven or preempted run reports
        rz = stoke.resilience_summary or {}
        result["resilience"] = True
        result["restarts"] = rz.get("restarts")
        result["resumed_step"] = rz.get("resumed_step")
        result["lost_steps"] = rz.get("lost_steps")
        result["preemptions"] = rz.get("preemptions")
        result["emergency_saves"] = rz.get("emergency_saves")
        result["quarantined_ckpts"] = rz.get("quarantined_ckpts")
        # ISSUE 14 columns on the same geometry: (a) ckpt_stall_s — the
        # worst step-wall spike while a periodic async save fires, with
        # the offload staging path vs the legacy main-thread gather; (b)
        # elastic_resume — a manifest'd save restored onto a HALF-SIZE
        # mesh, params bit-checked.  Best-effort probes: a failure
        # records null columns, never kills the capture.
        import tempfile as _tf

        from stoke_tpu import CheckpointConfig as _CkptCfg

        def _ckpt_stall(offload: bool):
            cfg = _CkptCfg(async_save=True, offload_staging=offload,
                           max_to_keep=2)
            root = _tf.mkdtemp(prefix="stoke-bench-ckptstall-")
            name = "stall-offload" if offload else "stall-legacy"
            # warm the save path (first offload save compiles the
            # snapshot copy program; first legacy save warms the gather)
            stoke._save_with_config(root, name, cfg, None)
            stoke.wait_for_checkpoint()
            walls, save_wall = [], None
            for i in range(5):
                t0 = time.perf_counter()
                jax.block_until_ready(one_step(i))
                if i == 2:
                    stoke._save_with_config(root, name, cfg, None)
                    save_wall = time.perf_counter() - t0
                else:
                    walls.append(time.perf_counter() - t0)
            stoke.wait_for_checkpoint()
            quiet = sorted(walls)[len(walls) // 2]
            return max(0.0, save_wall - quiet)

        try:
            result["ckpt_stall_offload_s"] = round(_ckpt_stall(True), 4)
            result["ckpt_stall_legacy_s"] = round(_ckpt_stall(False), 4)
            result["ckpt_stall_s"] = result["ckpt_stall_offload_s"]
        except Exception as e:
            print(f"bench: ckpt-stall probe failed: {e!r}", file=sys.stderr)
            result["ckpt_stall_offload_s"] = None
            result["ckpt_stall_legacy_s"] = None
            result["ckpt_stall_s"] = None
        elastic_ok = None
        try:
            mesh = stoke._mesh
            n_dev = int(mesh.size) if mesh is not None else 1
            # the probe needs a mesh to shrink: distributed runs only
            # (single-device captures record null — nothing to re-shard)
            if n_dev >= 2 and stoke.resilience is not None:
                from stoke_tpu import MeshConfig as _MeshCfg
                from stoke_tpu import ResilienceConfig as _RzCfg

                el_root = _tf.mkdtemp(prefix="stoke-bench-elastic-")
                stoke._save_with_config(
                    el_root, "emergency", _CkptCfg(), None
                )
                from stoke_tpu import TelemetryConfig as _TelCfg

                half = np.array(list(mesh.devices.flat)[: n_dev // 2])
                half_cfgs = [
                    _TelCfg(
                        output_dir=_tf.mkdtemp(
                            prefix="stoke-bench-elastic-tel-"
                        ),
                        log_every_n_steps=10, prometheus=False,
                        sample_device_time=False,
                    )
                    if isinstance(c, _TelCfg)
                    else c
                    for c in run_configs
                    if not isinstance(c, _RzCfg)
                ] + [
                    _RzCfg(save_path=el_root),
                    _MeshCfg(devices=half),
                ]
                ref = [
                    np.asarray(l)
                    for l in jax.tree_util.tree_leaves(stoke.params)
                ]
                half_stoke = _build_stoke(elastic_variables, half_cfgs)
                elastic_ok = bool(half_stoke.resume()) and all(
                    np.array_equal(np.asarray(a), b)
                    for a, b in zip(
                        jax.tree_util.tree_leaves(half_stoke.params), ref
                    )
                )
                half_stoke.close_telemetry()
        except Exception as e:
            print(f"bench: elastic-resume probe failed: {e!r}",
                  file=sys.stderr)
            elastic_ok = None
        result["elastic_resume"] = elastic_ok
    if args.tuned:
        # tuned/cache columns (ISSUE 6): the winner being replayed and
        # whether this capture warm-started from the compile cache
        cc = stoke.compile_cache
        result["tuned"] = True
        result["tuned_config_key"] = (tuned_rec or {}).get("config_key")
        result["cache_hit"] = cc.hits
        result["cache_miss"] = cc.misses
        result["cache_saved_compile_s"] = round(cc.saved_compile_s, 3)
    if (args.health or args.attribution_peak_tflops or args.fleet
            or args.resilience or args.trace or args.numerics
            or args.memory):
        stoke.close_telemetry()
    if on_accel:
        regression = check_regression(
            result["metric"],
            result["value"],
            config={
                "xla_flags": args.xla_flags or None,
                "steps_per_dispatch": per_call,
                "comm_dtype": args.comm_dtype,
                "comm_shard_tier": shard_tier,
                "tuned": True if args.tuned else None,
                "health": True if args.health else None,
                "attribution": (
                    True if args.attribution_peak_tflops else None
                ),
                "fleet": True if args.fleet else None,
                "resilience": True if args.resilience else None,
                "trace": True if args.trace else None,
                "numerics": True if args.numerics else None,
                "memory": True if args.memory else None,
            },
        )
        if regression is not None:
            # loud, structured, and on both streams: the JSON line carries
            # the flag for the driver, stderr for a human scanning logs
            result["regression"] = regression
            print(
                f"bench.py REGRESSION: {result['metric']} fresh "
                f"{result['value']} is {regression['ratio']:.2%} of ledger "
                f"best {regression['best']}",
                file=sys.stderr,
            )
    print(json.dumps(result))
    # persist here too (not only in the supervisor): inside
    # scripts/tpu_session.py the worker runs directly, with no supervisor
    # to parse and record the line.  Idempotent with the supervisor's write.
    if on_accel:
        _persist_result(
            result["metric"],
            {
                "value": result["value"],
                "unit": result["unit"],
                "vs_baseline": result["vs_baseline"],
                "date": result["measured_on"],
                "api": api,
                "batch": batch,
                "steps_per_dispatch": per_call,
                "source": "bench.py fresh capture",
                "backend": jax.default_backend(),
                **({"xla_flags": args.xla_flags} if args.xla_flags else {}),
                **({"comm_dtype": args.comm_dtype} if args.comm_dtype else {}),
                **(
                    {
                        "comm_shard_tier": shard_tier,
                        "comm_grad_bytes_prequant": result[
                            "comm_grad_bytes_prequant"
                        ],
                        "comm_grad_bytes_onwire": result[
                            "comm_grad_bytes_onwire"
                        ],
                        "comm_bytes_param_gather": result[
                            "comm_bytes_param_gather"
                        ],
                        "comm_compression": result["comm_compression"],
                    }
                    if shard_tier
                    else {}
                ),
                **(
                    {
                        "tuned": True,
                        "tuned_config_key": result["tuned_config_key"],
                        "cache_hit": result["cache_hit"],
                        "cache_miss": result["cache_miss"],
                    }
                    if args.tuned
                    else {}
                ),
                **(
                    {
                        "health": True,
                        "health_anomalies": result["health_anomalies"],
                    }
                    if args.health
                    else {}
                ),
                **(
                    {
                        "fleet": True,
                        "fleet_hosts": result["fleet_hosts"],
                        "fleet_windows": result["fleet_windows"],
                        "fleet_straggler_windows": result[
                            "fleet_straggler_windows"
                        ],
                        "fleet_last_lag_frac": result["fleet_last_lag_frac"],
                        "fleet_last_skew_class": result[
                            "fleet_last_skew_class"
                        ],
                    }
                    if args.fleet
                    else {}
                ),
                **(
                    {
                        "trace": True,
                        "trace_overhead_frac": result["trace_overhead_frac"],
                        "trace_overhead_ok": result["trace_overhead_ok"],
                        "trace_spans": result["trace_spans"],
                    }
                    if args.trace
                    else {}
                ),
                **(
                    {
                        "numerics": True,
                        "numerics_groups": result["numerics_groups"],
                        "numerics_overhead_frac": result[
                            "numerics_overhead_frac"
                        ],
                        "numerics_overhead_ok": result[
                            "numerics_overhead_ok"
                        ],
                    }
                    if args.numerics
                    else {}
                ),
                **(
                    {
                        "memory": True,
                        "mem_resident_bytes": result["mem_resident_bytes"],
                        "mem_temp_peak_bytes": result[
                            "mem_temp_peak_bytes"
                        ],
                        "mem_headroom_frac": result["mem_headroom_frac"],
                    }
                    if args.memory
                    else {}
                ),
                **(
                    {
                        "resilience": True,
                        "restarts": result["restarts"],
                        "resumed_step": result["resumed_step"],
                        "lost_steps": result["lost_steps"],
                        "preemptions": result["preemptions"],
                        "emergency_saves": result["emergency_saves"],
                        "quarantined_ckpts": result["quarantined_ckpts"],
                    }
                    if args.resilience
                    else {}
                ),
                **(
                    {
                        "attribution": True,
                        "peak_tflops": args.attribution_peak_tflops,
                        "mfu": result["mfu"],
                        "achieved_tflops": result["achieved_tflops"],
                        "goodput_fraction": result["goodput_fraction"],
                        "goodput_s": result["goodput_s"],
                    }
                    if args.attribution_peak_tflops
                    else {}
                ),
            },
            keep_best=True,
        )


if __name__ == "__main__":
    main()

"""Benchmark: CIFAR-10 ResNet-50 training throughput through the Stoke facade.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Measures steady-state images/sec of the full framework path (4-call facade,
fused compiled micro-step, bf16 precision policy) on whatever accelerator JAX
exposes (the driver runs this on one real TPU chip).

Baseline: the reference publishes no numbers (BASELINE.md); the north star is
"CIFAR-10 ResNet-50 per-chip throughput matching an A100 running the
reference under DDP+AMP".  ``A100_BASELINE_IMGS_PER_SEC`` encodes that
comparison point as a fixed constant (estimate for ResNet-50 @ 32x32 CIFAR,
batch 256, AMP, single A100 — CIFAR images are ~50x cheaper than ImageNet's
224x224, so this is far above ImageNet-scale numbers).  ``vs_baseline`` is
value / baseline (>1.0 = faster than the A100 estimate).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

A100_BASELINE_IMGS_PER_SEC = 20000.0
WATCHDOG_SECONDS = 1500

#: Last completed on-chip measurement of this metric (train_steps api,
#: batch 256, real v5e — BENCH_NOTES.md round-2 sweep, 2026-07-29).  The
#: remote-TPU tunnel in this environment wedges for long stretches; when a
#: fresh measurement is impossible the error JSON carries this value under
#: ``measured_earlier`` so a 0.0 is never mistaken for "the framework is
#: slow" (the value is NOT reported as the live measurement).
LAST_GOOD_IMGS_PER_SEC = 9257.0


def _fail_json(detail: str) -> str:
    return json.dumps(
        {
            "metric": "cifar10_resnet50_bf16_train_throughput",
            "value": 0.0,
            "unit": "imgs/sec/chip",
            "vs_baseline": 0.0,
            "error": detail,
            "measured_earlier": LAST_GOOD_IMGS_PER_SEC,
            "measured_earlier_vs_baseline": round(
                LAST_GOOD_IMGS_PER_SEC / A100_BASELINE_IMGS_PER_SEC, 4
            ),
            "measured_earlier_note": "real-v5e number from this round; see BENCH_NOTES.md",
        }
    )


def _supervise(argv) -> int:
    """Run the real bench in a subprocess with a watchdog.

    The TPU in this environment is reached through a remote tunnel that can
    wedge; a wedged tunnel hangs *any* process at jax import.  This wrapper
    (which never imports jax) guarantees the driver always gets its one JSON
    line, even if the measurement process hangs or dies.
    """
    # fast pre-probe: a wedged remote-TPU tunnel hangs any jax process at
    # backend init; spend 120s finding that out instead of the full watchdog
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=120,
        )
        if probe.returncode != 0:
            raise RuntimeError(
                (probe.stderr or "device probe failed").strip().splitlines()[-1][:200]
            )
    except subprocess.TimeoutExpired:
        print(_fail_json("device probe timed out (TPU tunnel wedged)"))
        return 1
    except RuntimeError as e:
        print(_fail_json(str(e)))
        return 1
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--_worker", *argv],
            capture_output=True,
            text=True,
            timeout=WATCHDOG_SECONDS,
        )
        for line in reversed(out.stdout.strip().splitlines()):
            if line.startswith("{"):
                print(line)
                return 0
        err = (out.stderr or "no JSON output").strip().splitlines()
        detail = err[-1][:200] if err else "unknown"
    except subprocess.TimeoutExpired:
        detail = f"timeout after {WATCHDOG_SECONDS}s (TPU tunnel wedged?)"
    print(_fail_json(detail))
    return 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", choices=["full", "tiny"], default="full",
                    help="tiny = CPU-safe smoke (BasicNN, few steps)")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--warmup", type=int, default=None)
    ap.add_argument("--api", choices=["4call", "train_step", "train_steps"],
                    default="train_steps",
                    help="facade path to measure; train_steps (multi-step "
                    "scan, one dispatch per N optimizer steps) is the "
                    "fastest measured (scripts/bench_sweep.py)")
    ap.add_argument("--_worker", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()
    if not args._worker:
        sys.exit(_supervise(sys.argv[1:]))

    import numpy as np

    import jax
    import optax

    from stoke_tpu import Stoke, StokeOptimizer
    from stoke_tpu.models import BasicNN, ResNet50

    tiny = args.preset == "tiny"
    on_accel = jax.default_backend() not in ("cpu",)
    batch = args.batch or (16 if tiny else 256)
    steps = args.steps or (3 if tiny else 30)
    warmup = args.warmup if args.warmup is not None else (1 if tiny else 5)

    if tiny:
        model = BasicNN()
    else:
        model = ResNet50(num_classes=10, cifar_stem=True)
    from stoke_tpu.utils import init_module

    variables = init_module(
        model, jax.random.PRNGKey(0), np.zeros((2, 32, 32, 3), np.float32), train=False
    )
    stoke = Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.05, "momentum": 0.9}
        ),
        loss=lambda logits, labels: optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean(),
        params=variables,
        batch_size_per_device=batch,
        device="tpu" if on_accel else "cpu",
        precision=None if tiny else "bf16",
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        verbose=False,
    )

    # Pre-place a rotating pool of device batches: this measures the training
    # step itself (host->HBM transfer overlap is the DataLoader's job and the
    # tunnel used in CI makes per-step device_put non-representative).
    r = np.random.default_rng(0)
    api = args.api
    per_call = 1
    if api == "train_steps":
        # multi-step scan: SEG optimizer steps per compiled dispatch
        SEG = 10
        xs = jax.device_put(r.normal(size=(SEG, batch, 32, 32, 3)).astype(np.float32))
        ys = jax.device_put(r.integers(0, 10, size=(SEG, batch)))
        per_call = SEG
        steps = max(3, steps // SEG)
        warmup = min(warmup, 1)  # each warmup call is already SEG steps

        def one_step(i):
            return stoke.train_steps(xs, (ys,))
    else:
        pool = [
            (
                jax.device_put(r.normal(size=(batch, 32, 32, 3)).astype(np.float32)),
                jax.device_put(r.integers(0, 10, size=(batch,))),
            )
            for _ in range(4)
        ]

        def one_step(i):
            x, y = pool[i % len(pool)]
            if api == "train_step":
                return stoke.train_step(x, (y,))
            out = stoke.model(x)
            loss = stoke.loss(out, y)
            stoke.backward(loss)
            stoke.step()
            return loss

    def timed(n):
        """Wall time for n steps with a forced device fetch at the end
        (block_until_ready is unreliable through remote-device tunnels)."""
        t0 = time.perf_counter()
        last = None
        for i in range(n):
            last = one_step(i)
        np.asarray(jax.tree_util.tree_leaves(last)[0])  # real sync: fetch scalar
        return time.perf_counter() - t0

    for i in range(warmup):
        one_step(i)
    timed(1)
    # delta timing: (t(2n) - t(n)) / n cancels fixed sync/tunnel overhead
    t1 = timed(steps)
    t2 = timed(2 * steps)
    dt = max(t2 - t1, 1e-9)

    imgs_per_sec = batch * steps * per_call / dt
    print(
        json.dumps(
            {
                "metric": "cifar10_resnet50_bf16_train_throughput"
                if not tiny
                else "cifar10_basicnn_train_throughput",
                "value": round(imgs_per_sec, 1),
                "unit": "imgs/sec/chip",
                "vs_baseline": round(imgs_per_sec / A100_BASELINE_IMGS_PER_SEC, 4),
                "api": api,
                "batch": batch,
                "steps_per_dispatch": per_call,
            }
        )
    )


if __name__ == "__main__":
    main()

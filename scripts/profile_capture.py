"""Evidence capture for the ResNet throughput gap (VERDICT r3 item 2).

Round-2 measured 9,257 imgs/sec/chip at batch 256 *falling* to 7,786 at
1024, with no profiler/HLO evidence explaining why.  This script captures,
in one tunnel session:

  1. batch sweep — train_steps imgs/sec at several batch sizes (the
     falls-with-batch reproduction), persisted per batch;
  2. wall-clock breakdown — the facade's phase timers after the sweep;
  3. optimized-HLO dump of the fused optimizer step (batch 256 and the
     sweep's worst batch): op-category histogram (convolution / fusion /
     reduce / collectives / copies) printed, full text gzipped into
     artifacts/ for offline reading;
  4. optional jax.profiler trace (--trace-dir) around 3 steps.

Flags A/B: pass extra XLA flags via --xla-flags; they are applied to
XLA_FLAGS BEFORE jax import in the worker, so autotune experiments
(e.g. --xla_tpu_enable_experimental_fusion_cost_model=true) are one
flag away and land in the printed records.

Run serialized on the TPU (supervised; tunnel is single-client):
    python scripts/profile_capture.py --batches 128,256,512,1024
"""

from __future__ import annotations

import gzip
import json
import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)

from _supervise import supervise  # noqa: E402


def _hlo_histogram(text: str) -> dict:
    cats = {
        "convolution": 0, "fusion": 0, "all-reduce": 0, "all-gather": 0,
        "reduce-scatter": 0, "copy": 0, "transpose": 0, "reduce": 0,
        "custom-call": 0,
    }
    for line in text.splitlines():
        ls = line.lstrip()
        for cat in cats:
            if ls.startswith(f"%{cat}") or f" = {cat}(" in ls or (
                cat + "." in ls.split("=")[-1][:40] if "=" in ls else False
            ):
                cats[cat] += 1
                break
    cats["total_lines"] = len(text.splitlines())
    return cats


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--_worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--batches", default="128,256,512,1024")
    ap.add_argument("--xla-flags", default="",
                    help="extra XLA_FLAGS for the worker (A/B autotune runs)")
    ap.add_argument("--trace-dir", default="",
                    help="capture a jax.profiler trace into this dir")
    ap.add_argument("--seg", type=int, default=10)
    ap.add_argument("--smoke", action="store_true",
                    help="CPU flow validation: narrow ResNet-18, tiny "
                    "batches (results meaningless)")
    args = ap.parse_args()
    if args.smoke:
        args.batches = "8,16"
        args.seg = 2
    if not args._worker:
        if args.xla_flags:
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + " " + args.xla_flags
            ).strip()
        sys.exit(supervise(__file__, sys.argv[1:]))

    import jax
    import optax

    from stoke_tpu import ProfilerConfig, Stoke, StokeOptimizer
    from stoke_tpu.models import ResNet50
    from stoke_tpu.utils import init_module

    from _timing import delta_time

    r = np.random.default_rng(0)
    batches = [int(b) for b in args.batches.split(",")]
    SEG = args.seg
    on_accel = jax.default_backend() != "cpu"
    if args.smoke:
        from stoke_tpu.models import ResNet18

        model = ResNet18(num_classes=10, num_filters=8, cifar_stem=True)
    else:
        model = ResNet50(num_classes=10, cifar_stem=True)
    variables = init_module(
        model, jax.random.PRNGKey(0), np.zeros((2, 32, 32, 3), np.float32),
        train=False,
    )
    artifacts = os.path.join(REPO, "artifacts")
    os.makedirs(artifacts, exist_ok=True)

    def make_stoke(batch):
        return Stoke(
            model=model,
            optimizer=StokeOptimizer(
                optimizer=optax.sgd,
                optimizer_kwargs={"learning_rate": 0.05, "momentum": 0.9},
            ),
            loss=lambda lo, la: (
                optax.softmax_cross_entropy_with_integer_labels(lo, la).mean()
            ),
            params=jax.tree_util.tree_map(lambda a: a.copy(), variables),
            batch_size_per_device=batch,
            device="tpu" if on_accel else "cpu",
            precision="bf16",
            model_train_kwargs={"train": True},
            model_eval_kwargs={"train": False},
            # without this the facade's phase timers are nullcontexts and
            # the wall_clock probe would print empty
            configs=[ProfilerConfig(wall_clock_breakdown=True)],
            verbose=False,
        )

    results = []
    for batch in batches:
        stoke = make_stoke(batch)
        xs = jax.device_put(
            r.normal(size=(SEG, batch, 32, 32, 3)).astype(np.float32))
        ys = jax.device_put(r.integers(0, 10, size=(SEG, batch)))
        t_seg = delta_time(lambda: stoke.train_steps(xs, (ys,)), 3)
        rec = {
            "probe": "batch_sweep",
            "batch": batch,
            "step_ms": round(t_seg / SEG * 1e3, 3),
            "imgs_per_sec": round(batch * SEG / t_seg, 1),
            "xla_flags": args.xla_flags or None,
        }
        results.append(rec)
        print(json.dumps(rec), flush=True)

        if batch == 256 or batch == batches[-1]:
            # (smoke included: the HLO lower/compile path is the point)
            # optimized HLO of the fused step at this batch
            x1 = jax.device_put(
                r.normal(size=(batch, 32, 32, 3)).astype(np.float32))
            y1 = jax.device_put(r.integers(0, 10, size=(batch,)))
            try:
                from stoke_tpu.engine import DeferredOutput as _D
                from stoke_tpu.facade import is_deferred

                sentinel = _D(None, -1)
                flat, treedef = jax.tree_util.tree_flatten(
                    ((sentinel, y1), {}), is_leaf=is_deferred)
                arrays = stoke._place_batch(
                    [l for l in flat if not is_deferred(l)])
                dinfo = tuple((i, l._path) for i, l in enumerate(flat)
                              if is_deferred(l))
                fn = stoke._engine._build_fused(treedef, dinfo, True)
                compiled = fn.lower(
                    stoke._variables, stoke._opt_state, stoke._grad_buf,
                    stoke._scaler_state, stoke._comm_state, stoke._rng,
                    stoke._place_batch((x1,)), {}, arrays,
                ).compile()
                text = compiled.as_text()
                hist = _hlo_histogram(text)
                path = os.path.join(
                    artifacts, f"hlo_resnet50_bs{batch}.txt.gz")
                with gzip.open(path, "wt") as f:
                    f.write(text)
                print(json.dumps({"probe": "hlo_dump", "batch": batch,
                                  "path": os.path.relpath(path, REPO),
                                  **hist}), flush=True)
            except Exception as e:
                print(json.dumps({"probe": "hlo_dump", "batch": batch,
                                  "error": str(e)[:200]}), flush=True)

        if args.trace_dir and batch == 256:
            with jax.profiler.trace(args.trace_dir):
                for _ in range(3):
                    stoke.train_steps(xs, (ys,))
                stoke.block_until_ready()
            print(json.dumps({"probe": "trace", "dir": args.trace_dir}),
                  flush=True)
        print(json.dumps({"probe": "wall_clock", "batch": batch,
                          **{k: round(v, 3) for k, v in
                             stoke.wall_clock_breakdown.items()}}),
              flush=True)
        del stoke, xs, ys

    if len(results) > 1:
        best = max(results, key=lambda r: r["imgs_per_sec"])
        worst = min(results, key=lambda r: r["imgs_per_sec"])
        print(json.dumps({
            "probe": "sweep_summary",
            "best": {k: best[k] for k in ("batch", "imgs_per_sec")},
            "worst": {k: worst[k] for k in ("batch", "imgs_per_sec")},
            "falls_with_batch": results[-1]["imgs_per_sec"]
            < results[0]["imgs_per_sec"],
        }), flush=True)

    # segment-length sweep at the headline batch: each train_steps dispatch
    # is one host->device round trip; through the remote relay the
    # per-step share of that latency is RTT/SEG, so if throughput rises
    # with SEG the gap is dispatch latency (recoverable by config), not
    # compute.  delta_time cancels FIXED overhead but not per-dispatch
    # cost.  Runs AFTER the summary, each arm fenced, so a seg-arm failure
    # (OOM on the 50-step stack, tunnel hiccup) cannot lose the evidence
    # the batch sweep already paid tunnel time for.
    seg_batch = 256 if 256 in batches else batches[0]
    for seg in (10, 25, 50):
        if args.smoke and seg > 10:
            break
        if seg == SEG:
            # the batch sweep already measured this exact configuration —
            # reuse it instead of paying tunnel time for a duplicate point
            prior = next(r for r in results if r["batch"] == seg_batch)
            print(json.dumps({
                "probe": "seg_sweep", "batch": seg_batch, "seg": seg,
                "step_ms": prior["step_ms"],
                "imgs_per_sec": prior["imgs_per_sec"],
                "reused_from_batch_sweep": True,
            }), flush=True)
            continue
        stoke = xs = ys = None
        try:
            stoke = make_stoke(seg_batch)
            xs = jax.device_put(
                r.normal(size=(seg, seg_batch, 32, 32, 3)).astype(np.float32))
            ys = jax.device_put(r.integers(0, 10, size=(seg, seg_batch)))
            t = delta_time(lambda: stoke.train_steps(xs, (ys,)), 3)
            print(json.dumps({
                "probe": "seg_sweep", "batch": seg_batch, "seg": seg,
                "step_ms": round(t / seg * 1e3, 3),
                "imgs_per_sec": round(seg_batch * seg / t, 1),
            }), flush=True)
        except Exception as e:
            print(json.dumps({"probe": "seg_sweep", "seg": seg,
                              "error": str(e)[:200]}), flush=True)
        finally:
            # release THIS arm's HBM before the next (larger) arm allocates
            # — a failed seg-25 stack left referenced would cascade the
            # anticipated OOM into the seg-50 point
            del stoke, xs, ys


if __name__ == "__main__":
    main()

"""Reachability bound for the 20,000 imgs/sec north-star constant.

VERDICT r5 #1: the perf story ("0.46x and attacking") is unfalsifiable
until someone bounds what a v5e chip can physically do on cifar-stem
ResNet-50.  This script needs NO tunnel: ``Stoke.estimate_step_flops``
(XLA cost analysis) works on the CPU backend, and the arithmetic from
FLOPs/img to implied TFLOP/s at a target imgs/sec is exact.

For each batch it prints one JSON line and finally a markdown table ready
for BENCH_NOTES.md / docs/performance.md:

  - flops/step (XLA cost analysis of the FULL fused optimizer step:
    forward + backward + SGD-momentum update, bf16 policy)
  - flops/img
  - implied TFLOP/s at the round-2 measured throughput (where one exists)
  - implied TFLOP/s and MFU at the 20,000 imgs/sec baseline constant
  - MFU against v5e bf16 peak (197 TFLOP/s, the public v5e spec)

Run:  JAX_PLATFORMS=cpu python scripts/reachability_table.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: public TPU v5e peak (dense bf16); the MFU denominator for the table
V5E_BF16_PEAK_TFLOPS = 197.0

#: round-2 measured imgs/sec (BENCH_NOTES.md batch/API sweep, train_steps)
MEASURED_IMGS_PER_SEC = {256: 9257.0, 512: 8411.4, 1024: 7786.1}

#: the baseline constant encoded in bench.py
BASELINE_IMGS_PER_SEC = 20000.0


def build_stoke(batch, *, cifar=True):
    import jax
    import optax

    from stoke_tpu import Stoke, StokeOptimizer
    from stoke_tpu.models import ResNet50
    from stoke_tpu.utils import init_module

    side = 32 if cifar else 224
    classes = 10 if cifar else 1000
    model = ResNet50(num_classes=classes, cifar_stem=cifar)
    variables = init_module(
        model, jax.random.PRNGKey(0),
        np.zeros((2, side, side, 3), np.float32), train=False,
    )
    return Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.sgd,
            optimizer_kwargs={"learning_rate": 0.05, "momentum": 0.9},
        ),
        loss=lambda lo, la: __import__("optax")
        .softmax_cross_entropy_with_integer_labels(lo, la).mean(),
        params=variables,
        batch_size_per_device=batch,
        device="cpu" if jax.default_backend() == "cpu" else "tpu",
        precision="bf16",
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        verbose=False,
    ), side, classes


def probe(batch, *, cifar=True):
    import jax

    stoke, side, classes = build_stoke(batch, cifar=cifar)
    r = np.random.default_rng(0)
    x = jax.device_put(r.normal(size=(batch, side, side, 3)).astype(np.float32))
    y = jax.device_put(r.integers(0, classes, size=(batch,)))
    flops = stoke.estimate_step_flops(x, (y,))
    del stoke
    if flops is None:
        return None
    per_img = flops / batch
    rec = {
        "probe": "reachability",
        "config": "cifar32" if cifar else "imagenet224",
        "batch": batch,
        "gflops_per_step": round(flops / 1e9, 2),
        "mflops_per_img": round(per_img / 1e6, 2),
        "tflops_at_baseline_20k": round(per_img * BASELINE_IMGS_PER_SEC / 1e12, 3),
        "mfu_at_baseline_20k": round(
            per_img * BASELINE_IMGS_PER_SEC / 1e12 / V5E_BF16_PEAK_TFLOPS, 4
        ),
    }
    measured = MEASURED_IMGS_PER_SEC.get(batch) if cifar else None
    if measured:
        rec["measured_imgs_per_sec_r2"] = measured
        rec["tflops_at_measured"] = round(per_img * measured / 1e12, 3)
        rec["mfu_at_measured"] = round(
            per_img * measured / 1e12 / V5E_BF16_PEAK_TFLOPS, 4
        )
    print(json.dumps(rec), flush=True)
    return rec


def probe_serving(max_seqs=8):
    """Serving reachability row (ISSUE 18): the serve cost cards bound
    what one v5e chip could do on the bench arm's GPT-small decode loop.
    The decode program's roofline time at the v5e peaks is the attainable
    TPOT, so ``max_seqs / attainable_tpot_s`` is the attainable steady-
    state tokens/s/chip — exact arithmetic from the XLA cost analysis,
    no tunnel needed (the CPU backend lowers the same programs).  The
    measured leg cites the bench ledger's persisted on-chip serve
    capture when one exists."""
    import jax

    from stoke_tpu.configs import AttributionConfig, ServeConfig
    from stoke_tpu.models.gpt import GPT
    from stoke_tpu.serving import ServingEngine
    from stoke_tpu.utils import init_module

    # the bench --serve non-tiny arm's geometry (bench.py build_engine)
    model = GPT(
        vocab_size=8192, size_name="small", max_len=512, dropout_rate=0.0
    )
    variables = init_module(
        model, jax.random.PRNGKey(0), np.zeros((1, 8), np.int32),
        train=False,
    )
    cfg = ServeConfig(
        max_seqs=max_seqs, kv_block_size=16, max_seq_len=256,
        max_new_tokens=8, prefill_pad_multiple=32, cost_cards=True,
    )
    eng = ServingEngine(
        model, variables["params"], cfg,
        attribution=AttributionConfig(
            peak_tflops=V5E_BF16_PEAK_TFLOPS, peak_hbm_gbps=819.0
        ),
    )
    r = np.random.default_rng(0)
    for _ in range(2):  # one prefill bucket + the decode program
        eng.submit(r.integers(1, 8192, size=24).astype(np.int32))
    eng.run()
    cost = eng.summary()["cost"]
    att = cost["attainable_tpot_s"]
    if att is None:
        return None
    rec = {
        "probe": "reachability",
        "config": f"gpt_small_serve (max_seqs={max_seqs})",
        "flops_per_token": round(cost["flops_per_token"] or 0.0, 1),
        "decode_bound": cost["decode_bound"],
        "attainable_tpot_s": round(att, 9),
        "attainable_tokens_per_sec_chip": round(max_seqs / att, 1),
    }
    # measured leg: the persisted on-chip bench capture, when one exists
    try:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        import bench

        ledger_rec = bench._load_results().get("gpt_small_serve_throughput")
        if ledger_rec and bench.record_backend(ledger_rec) not in (
            "cpu", "unknown"
        ):
            rec["measured_tokens_per_sec"] = ledger_rec["value"]
            rec["roofline_fraction"] = round(
                ledger_rec["value"] / (max_seqs / att), 4
            )
    except Exception:
        pass
    print(json.dumps(rec), flush=True)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="128,256,512,1024")
    ap.add_argument("--skip-224", action="store_true")
    ap.add_argument(
        "--skip-serve", action="store_true",
        help="skip the serving reachability row (ISSUE 18)",
    )
    args = ap.parse_args()

    rows = []
    for b in (int(x) for x in args.batches.split(",")):
        rec = probe(b, cifar=True)
        if rec:
            rows.append(rec)
    if not args.skip_224:
        rec = probe(64, cifar=False)
        if rec:
            rows.append(rec)
    serve_row = None if args.skip_serve else probe_serving()

    # markdown for BENCH_NOTES.md / docs/performance.md
    print("\n| config | batch | MFLOPs/img | TFLOP/s @ measured (MFU) | "
          "TFLOP/s @ 20k (MFU) |")
    print("|---|---|---|---|---|")
    for r in rows:
        meas = (
            f"{r['tflops_at_measured']} ({r['mfu_at_measured']:.1%} "
            f"@ {r['measured_imgs_per_sec_r2']:.0f} img/s)"
            if "tflops_at_measured" in r else "—"
        )
        print(
            f"| {r['config']} | {r['batch']} | {r['mflops_per_img']} | "
            f"{meas} | {r['tflops_at_baseline_20k']} "
            f"({r['mfu_at_baseline_20k']:.1%}) |"
        )
    if serve_row:
        # serving reachability (ISSUE 18): attainable tokens/s/chip at
        # the v5e peaks from the decode-family cost card, beside the
        # ledger's measured on-chip capture when one exists
        meas = (
            f"{serve_row['measured_tokens_per_sec']:.0f} tok/s "
            f"({serve_row['roofline_fraction']:.1%} of roofline)"
            if "measured_tokens_per_sec" in serve_row else "—"
        )
        print(
            f"| {serve_row['config']} | — | "
            f"{serve_row['flops_per_token'] / 1e6:.1f} MFLOPs/tok | "
            f"{meas} | attainable "
            f"{serve_row['attainable_tokens_per_sec_chip']:.0f} tok/s/chip "
            f"({serve_row['decode_bound']}-bound) |"
        )


if __name__ == "__main__":
    main()

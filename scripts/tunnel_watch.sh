#!/bin/bash
# Tunnel watcher (round 4): probe the single-client TPU relay every ~10 min
# with a 120s timeout; the moment a probe succeeds, run the full measurement
# session (scripts/tpu_session.py) which holds /tmp/tpu_in_use for its
# duration.  One probe process at a time; never probe while a session runs.
#
#   nohup bash scripts/tunnel_watch.sh > /tmp/tunnel_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
# single-instance guard: a second concurrent watcher probing the
# single-client relay is itself a wedge trigger
exec 9>/tmp/tunnel_watch.lock
flock -n 9 || { echo "another tunnel_watch is already running; exiting"; exit 0; }
LOG=${TPU_SESSION_LOG:-/tmp/tpu_session_r05.log}
while true; do
  if [ -f /tmp/tpu_in_use ]; then
    # liveness, not bare existence: a SIGKILLed session never runs its
    # finally, and a stale lock would otherwise idle the watcher forever
    pid=$(cat /tmp/tpu_in_use 2>/dev/null)
    if [ -n "$pid" ] && kill -0 "$pid" 2>/dev/null; then
      echo "$(date -u +%H:%M:%S) session (pid $pid) holds tunnel; sleeping"
      sleep 600
      continue
    fi
    echo "$(date -u +%H:%M:%S) stale tunnel lock (pid ${pid:-?} dead); removing"
    rm -f /tmp/tpu_in_use
  fi
  echo "$(date -u +%H:%M:%S) probing tunnel..."
  # classifying probe (scripts/tunnel_probe.py): records the error CLASS
  # per attempt (tcp-refused / tcp-ok-probe-timeout / pjrt-error / ...)
  # into /tmp/tunnel_probe_log.jsonl so the outage distribution is data,
  # not "timed out" (VERDICT r5 #5); exit 0 = accelerator ALIVE
  if timeout 150 python scripts/tunnel_probe.py; then
    echo "$(date -u +%H:%M:%S) tunnel ALIVE -> launching tpu_session"
    python scripts/tpu_session.py >> "$LOG" 2>&1
    rc=$?
    echo "$(date -u +%H:%M:%S) tpu_session exited rc=$rc (log: $LOG)"
    if [ $rc -eq 0 ]; then
      echo "SESSION_COMPLETE"
      exit 0
    fi
    # session failed (likely mid-run wedge): back off longer, then resume probing
    sleep 1200
  else
    echo "$(date -u +%H:%M:%S) probe failed; class distribution so far:"
    python scripts/tunnel_probe.py --summarize || true
    sleep 600
  fi
done

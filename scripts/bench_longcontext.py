"""Model-level long-context benchmark: GPT through the Stoke facade,
flash vs dense attention, on one chip.

The kernel-level sweep (scripts/flash_tpu_check.py) showed the pallas
flash kernel 3.5x faster than dense at L=4096 and alone above the dense
OOM cliff at L=8192.  This script shows the same advantage END TO END:
full training steps (fwd+bwd+optimizer, bf16, fused train_step) of a GPT
LM through the facade, sweeping sequence length, for both attention_fn
choices.  Prints one JSON line per (L, attention) point.

Run serialized on the TPU (supervised; tunnel is single-client):
    python scripts/bench_longcontext.py [--size mini] [--batch 4]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _supervise import supervise  # noqa: E402


def build(size, L, batch, attention, vocab=2048, chunked_ce=False):
    import jax
    import optax

    from stoke_tpu import Stoke, StokeOptimizer
    from stoke_tpu.models.gpt import GPT
    from stoke_tpu.utils import init_module

    kwargs = {}
    if attention == "flash":
        from stoke_tpu.ops import make_flash_attention

        kwargs.update(attention_fn=make_flash_attention(causal=True),
                      attention_is_causal=True)
    if chunked_ce:
        # chunked LM-head CE: the [B, L, V] logits tensor is never
        # materialized (ops/chunked_ce.py) — the second long-context
        # memory cliff, composable with the flash kernel
        from stoke_tpu.ops import chunked_causal_lm_loss

        kwargs.update(chunked_head=True)
        loss = lambda out, labels: chunked_causal_lm_loss(out, labels)
    else:
        from stoke_tpu.models.gpt import causal_lm_loss

        loss = causal_lm_loss
    model = GPT(vocab_size=vocab, size_name=size, max_len=L,
                dropout_rate=0.0, **kwargs)
    ids = np.zeros((2, L), np.int32)
    variables = init_module(model, jax.random.PRNGKey(0), ids, train=False)
    on_accel = jax.default_backend() not in ("cpu",)
    return Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.adamw, optimizer_kwargs={"learning_rate": 3e-4}),
        loss=loss,
        params=variables,
        batch_size_per_device=batch,
        device="tpu" if on_accel else "cpu",
        precision="bf16" if on_accel else None,
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        verbose=False,
    )


def bench_ring_inner(lengths, batch, heads, head_dim):
    """Op-level arm: ring attention with dense vs flash inner math, fwd+bwd,
    over a ("data", "seq") mesh spanning every visible device.  On a single
    chip the ring degenerates to one hop — which is precisely the comparison
    that matters there: the dense inner materializes the [L, L] score block
    and falls off the OOM cliff at L≥8k while the flash inner keeps running.
    On the simulated 8-device CPU mesh the same code exercises the full
    multi-hop composition (per-hop flash + lse merge)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from stoke_tpu.ops import ring_attention

    from _timing import delta_time

    devs = np.asarray(jax.devices()).reshape(1, -1)
    mesh = Mesh(devs, ("data", "seq"))
    n = devs.size
    r = np.random.default_rng(0)
    results = []
    for L in lengths:
        mk = lambda: jnp.asarray(
            r.normal(size=(batch, heads, L, head_dim)).astype(np.float32),
            jnp.bfloat16,
        )
        q, k, v = mk(), mk(), mk()
        for inner in ("dense", "flash"):
            try:
                def loss(q, k, v):
                    out = ring_attention(
                        q, k, v, mesh=mesh, axis_name="seq", causal=True,
                        inner=inner,
                    )
                    return jnp.sum(out.astype(jnp.float32) ** 2)

                step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
                step(q, k, v)  # compile
                t = delta_time(lambda: step(q, k, v), 5)
                rec = {"bench": "ring_inner", "L": L, "batch": batch,
                       "heads": heads, "head_dim": head_dim, "devices": n,
                       "inner": inner, "fwdbwd_ms": round(t * 1e3, 2)}
            except Exception as e:
                rec = {"bench": "ring_inner", "L": L, "batch": batch,
                       "heads": heads, "head_dim": head_dim, "devices": n,
                       "inner": inner, "error": type(e).__name__}
            print(json.dumps(rec), flush=True)
            results.append(rec)
    ok = [p for p in results if "error" not in p]
    for L in sorted({p["L"] for p in ok}):
        d = next((p for p in ok if p["L"] == L and p["inner"] == "dense"), None)
        f = next((p for p in ok if p["L"] == L and p["inner"] == "flash"), None)
        if d and f:
            print(json.dumps({"bench": "ring_inner", "L": L,
                              "flash_inner_speedup": round(
                                  d["fwdbwd_ms"] / f["fwdbwd_ms"], 2)}),
                  flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--_worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--size", default="mini")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--lengths", default="1024,4096,8192")
    ap.add_argument("--op-ring", action="store_true",
                    help="op-level ring-inner arm (dense vs flash hop math) "
                    "instead of the model-level GPT sweep")
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--chunked-ce", action="store_true",
                    help="add a third arm: flash attention + chunked LM-head "
                    "CE (no [B, L, V] logits tensor)")
    args = ap.parse_args()
    if not args._worker:
        sys.exit(supervise(__file__, sys.argv[1:], watchdog_seconds=3000))

    import jax

    from _timing import delta_time

    if args.op_ring:
        bench_ring_inner(
            [int(x) for x in args.lengths.split(",")],
            args.batch, args.heads, args.head_dim,
        )
        return

    r = np.random.default_rng(0)
    results = []
    arms = [("dense", False), ("flash", False)]
    if args.chunked_ce:
        arms.append(("flash", True))
    for L in (int(x) for x in args.lengths.split(",")):
        ids = jax.device_put(
            r.integers(0, args.vocab, size=(args.batch, L)).astype(np.int32))
        for attention, chunked in arms:
            label = attention + ("+chunked_ce" if chunked else "")
            stoke = None
            try:
                stoke = build(args.size, L, args.batch, attention,
                              vocab=args.vocab, chunked_ce=chunked)
                t = delta_time(lambda: stoke.train_step(ids, (ids,)), 5)
                tok_s = args.batch * L / t
                rec = {"bench": "gpt_longcontext", "size": args.size,
                       "L": L, "batch": args.batch, "attention": label,
                       "vocab": args.vocab,
                       "step_ms": round(t * 1e3, 2),
                       "tok_per_sec": round(tok_s, 1)}
            except Exception as e:
                rec = {"bench": "gpt_longcontext", "size": args.size, "L": L,
                       "batch": args.batch, "attention": label,
                       "vocab": args.vocab,
                       "error": type(e).__name__}
            finally:
                # drop device state even when the step OOMs, or the dead
                # model's params/executables squat in HBM for the next arm
                del stoke
            print(json.dumps(rec), flush=True)
            results.append(rec)
    ok = [p for p in results if "error" not in p]
    for L in sorted({p["L"] for p in ok}):
        d = next((p for p in ok if p["L"] == L and p["attention"] == "dense"), None)
        f = next((p for p in ok if p["L"] == L and p["attention"] == "flash"), None)
        if d and f:
            print(json.dumps({"L": L, "flash_speedup": round(
                d["step_ms"] / f["step_ms"], 2)}), flush=True)


if __name__ == "__main__":
    main()

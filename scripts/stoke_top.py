"""stoke_top: a terminal dashboard over the live ops plane (ISSUE 20).

The ``top(1)`` of a stoke rank: polls ``/statusz`` + ``/requests`` on an
:class:`~stoke_tpu.telemetry.opsplane.OpsPlane` endpoint and redraws one
screen per interval — health verdict (the same 200/503 flip a load
balancer drains on), goodput / MFU / HBM ledger from the training block,
the serving engine's throughput + latency percentiles + SLO attainment,
and the in-flight request table with per-request TTFT deadline headroom.
Stdlib only (urllib + ANSI redraw); read-only against the plane, so it
is always safe to point at a production rank.

Usage (any host that can reach the plane's loopback/bound address):

    python scripts/stoke_top.py [--url http://127.0.0.1:9200]
        [--interval 2.0] [--once] [--no-clear]

``--once`` prints a single frame and exits (scriptable: the smoke and
docs examples use it); ``--interval`` is the redraw period in seconds.
Exit 0 on a clean run, 1 when the endpoint never answered.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def fetch(base: str, path: str, timeout: float = 5.0):
    """One GET against the plane; error statuses are data (503 is the
    drain verdict, not a failure of this tool)."""
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode())
        except ValueError:
            return e.code, None
    except (urllib.error.URLError, OSError, ValueError):
        return None, None


def _fmt(v, spec: str = "", none: str = "-") -> str:
    if v is None:
        return none
    return format(v, spec) if spec else str(v)


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0:
            return f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}TiB"


def render(statusz: dict, requests: dict) -> str:
    """One frame of the dashboard as a plain string (ANSI-free — the
    caller owns the clear/redraw discipline, and tests diff the text)."""
    lines = []
    healthy = statusz.get("healthy")
    verdict = (
        "HEALTHY" if healthy
        else f"HALTED ({statusz.get('halted')})" if healthy is False
        else "unknown"
    )
    lines.append(
        f"stoke_top — run={_fmt(statusz.get('run'))} "
        f"rank={_fmt(statusz.get('rank'))} "
        f"{_fmt(statusz.get('host'))}:{_fmt(statusz.get('port'))} "
        f"up={_fmt(statusz.get('uptime_s'), '.0f')}s  [{verdict}]  "
        f"anomalies={_fmt(statusz.get('anomalies'))}"
    )

    training = statusz.get("training") or {}
    goodput = training.get("goodput") or {}
    memory = training.get("memory") or {}
    trace = training.get("trace") or {}
    if training:
        lines.append(
            "train  "
            f"goodput={_fmt(goodput.get('goodput_fraction'), '.1%')} "
            f"windows={_fmt(goodput.get('windows'))} "
            f"mfu={_fmt(goodput.get('mfu'), '.2e')} "
            f"resident={_fmt_bytes(memory.get('resident_bytes'))} "
            f"headroom={_fmt_bytes(memory.get('headroom_bytes'))} "
            f"spans={_fmt(trace.get('spans'))}"
        )

    serving = statusz.get("serving") or {}
    if serving:
        slo = serving.get("slo") or {}
        lines.append(
            "serve  "
            f"completed={_fmt(serving.get('completed'))} "
            f"tokens={_fmt(serving.get('tokens_out'))} "
            f"kv_occ={_fmt(serving.get('kv_block_occupancy'), '.1%')} "
            f"ttft_p50={_fmt(serving.get('ttft_p50_s'), '.3f')}s "
            f"tpot_p50={_fmt(serving.get('tpot_p50_s'), '.4f')}s "
            f"slo_att={_fmt(slo.get('attainment'), '.1%')}"
        )

    rows = (requests or {}).get("requests") or []
    lines.append(
        f"requests ({len(rows)}"
        f"{'+, truncated' if (requests or {}).get('truncated') else ''})"
    )
    if rows:
        lines.append(
            f"  {'rid':>6} {'prio':<12} {'state':<10} {'tok':>5} "
            f"{'kvblk':>5} {'headroom_s':>10} {'age_s':>8}"
        )
        for r in rows:
            lines.append(
                f"  {_fmt(r.get('rid')):>6} "
                f"{_fmt(r.get('priority')):<12} "
                f"{_fmt(r.get('state')):<10} "
                f"{_fmt(r.get('tokens_out')):>5} "
                f"{_fmt(r.get('kv_blocks')):>5} "
                f"{_fmt(r.get('slo_headroom_s'), '+.2f'):>10} "
                f"{_fmt(r.get('age_s'), '.2f'):>8}"
            )
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="terminal dashboard over a live stoke ops plane"
    )
    ap.add_argument("--url", default="http://127.0.0.1:9200",
                    help="base URL of the rank's ops plane (multihost: "
                    "rank r listens on port + r)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="seconds between redraws")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit (scriptable)")
    ap.add_argument("--no-clear", action="store_true",
                    help="append frames instead of ANSI clear-and-redraw")
    args = ap.parse_args()
    base = args.url.rstrip("/")

    seen = False
    try:
        while True:
            _, statusz = fetch(base, "/statusz")
            _, requests = fetch(base, "/requests")
            if statusz is None:
                frame = (
                    f"stoke_top — {base}: no answer "
                    f"(plane down or run finished)"
                )
            else:
                seen = True
                frame = render(statusz, requests or {})
            if not args.no_clear and not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")
            print(frame, flush=True)
            if args.once:
                return 0 if statusz is not None else 1
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0 if seen else 1


if __name__ == "__main__":
    sys.exit(main())

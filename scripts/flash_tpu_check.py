"""On-TPU validation + microbenchmark for the Pallas flash-attention kernel.

Compiles NON-interpret on the real chip, checks forward and gradient numerics
against `stoke_tpu.ops.flash_attention.dense_reference` (the same reference
and tolerances the pytest gate `tests/test_flash_tpu.py` uses), then
benchmarks flash vs dense at L in {1024, 4096, 8192} (fwd and fwd+bwd),
printing one JSON line per point.

Run serially (the remote-TPU tunnel is single-client; a supervisor process
pre-probes + watchdogs the measurement):
    python scripts/flash_tpu_check.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_numerics():
    import jax
    import jax.numpy as jnp

    from stoke_tpu.ops.flash_attention import (
        BWD_RTOL_BF16,
        FWD_ATOL_BF16,
        dense_reference,
        flash_attention,
    )

    r = np.random.default_rng(0)
    B, H, L, D = 2, 4, 512, 64
    mk = lambda: jnp.asarray(
        r.normal(size=(B, H, L, D)).astype(np.float32), jnp.bfloat16
    )
    q, k, v = mk(), mk(), mk()
    mask = jnp.asarray((r.random(size=(B, L)) > 0.2).astype(np.int32))

    failures = []
    for causal in (False, True):
        for m in (None, mask):
            out = flash_attention(q, k, v, m, causal=causal, interpret=False)
            ref = dense_reference(q, k, v, m, causal=causal)
            err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
            ok = err < FWD_ATOL_BF16
            if not ok:
                failures.append((causal, m is not None, "fwd", err))
            print(json.dumps({"check": "fwd", "causal": causal,
                              "masked": m is not None,
                              "max_abs_err": round(err, 5), "ok": ok}),
                  flush=True)

            def loss_flash(q, k, v):
                return jnp.sum(
                    flash_attention(q, k, v, m, causal=causal,
                                    interpret=False).astype(jnp.float32) ** 2
                )

            def loss_dense(q, k, v):
                return jnp.sum(dense_reference(q, k, v, m, causal=causal) ** 2)

            gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
            gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
            gerr = max(
                float(jnp.max(jnp.abs(a.astype(jnp.float32) -
                                      b.astype(jnp.float32))))
                for a, b in zip(gf, gd)
            )
            # grads of sum-of-squares scale with L; tolerance is relative
            gscale = max(
                float(jnp.max(jnp.abs(b.astype(jnp.float32)))) for b in gd
            )
            gok = gerr < BWD_RTOL_BF16 * max(gscale, 1.0)
            if not gok:
                failures.append((causal, m is not None, "bwd", gerr))
            print(json.dumps({"check": "bwd", "causal": causal,
                              "masked": m is not None,
                              "max_abs_err": round(gerr, 5),
                              "grad_scale": round(gscale, 3), "ok": gok}),
                  flush=True)
    return failures


def bench():
    import jax
    import jax.numpy as jnp

    from stoke_tpu.ops.flash_attention import dense_reference, flash_attention

    r = np.random.default_rng(0)

    def timeit(f, *args, iters=20):
        f(*args)  # compile
        jax.block_until_ready(f(*args))
        t0 = time.perf_counter()
        for _ in range(iters):
            o = f(*args)
        jax.block_until_ready(o)
        t1 = time.perf_counter()
        for _ in range(2 * iters):
            o = f(*args)
        jax.block_until_ready(o)
        # delta timing can go sub-noise-floor negative for sub-ms kernels
        return max((time.perf_counter() - t1 - (t1 - t0)) / iters, 1e-6)

    best_blocks = {}
    for L in (1024, 4096, 8192):
        B, H, D = 4, 8, 64
        mk = lambda: jnp.asarray(
            r.normal(size=(B, H, L, D)).astype(np.float32), jnp.bfloat16
        )
        q, k, v = mk(), mk(), mk()

        # dense reference: materializes the [L, L] scores — expected to OOM
        # at large L (that memory cliff is the kernel's reason to exist)
        td = tgd = None
        try:
            dense_f = jax.jit(
                lambda q, k, v: dense_reference(q, k, v, causal=True)
                .astype(jnp.bfloat16))
            td = timeit(dense_f, q, k, v)
        except Exception as e:
            print(json.dumps({"bench": "dense_fwd_oom", "L": L,
                              "error": type(e).__name__}), flush=True)
        try:
            gdense = jax.jit(jax.grad(lambda q, k, v: jnp.sum(
                dense_reference(q, k, v, causal=True)), argnums=(0, 1, 2)))
            tgd = timeit(gdense, q, k, v, iters=10)
        except Exception as e:
            print(json.dumps({"bench": "dense_bwd_oom", "L": L,
                              "error": type(e).__name__}), flush=True)

        # block-size sweep: larger q blocks cut the K/V HBM refetch factor
        # (traffic ~ L^2 D / block_q), larger k blocks amortize the k sweep
        for bq, bk in ((128, 128), (256, 256), (256, 512), (512, 512)):
            if bq > L or bk > L:
                continue
            flash_f = jax.jit(lambda q, k, v, bq=bq, bk=bk: flash_attention(
                q, k, v, causal=True, block_q=bq, block_k=bk, interpret=False))
            tf = timeit(flash_f, q, k, v)
            gflash = jax.jit(jax.grad(lambda q, k, v, bq=bq, bk=bk: jnp.sum(
                flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk,
                                interpret=False)
                .astype(jnp.float32)), argnums=(0, 1, 2)))
            tgf = timeit(gflash, q, k, v, iters=10)
            rec = {
                "bench": "flash_vs_dense", "L": L, "B": B, "H": H, "D": D,
                "block_q": bq, "block_k": bk,
                "flash_fwd_ms": round(tf * 1e3, 3),
                "dense_fwd_ms": None if td is None else round(td * 1e3, 3),
                "fwd_speedup": None if td is None else round(td / tf, 2),
                "flash_fwdbwd_ms": round(tgf * 1e3, 3),
                "dense_fwdbwd_ms": None if tgd is None else round(tgd * 1e3, 3),
                "fwdbwd_speedup": None if tgd is None else round(tgd / tgf, 2),
            }
            print(json.dumps(rec), flush=True)
            cur = best_blocks.get(L)
            if cur is None or tgf < cur[1]:
                best_blocks[L] = ((bq, bk), tgf)
    print(json.dumps({"best_blocks": {
        str(L): {"blocks": list(bb), "fwdbwd_ms": round(t * 1e3, 3)}
        for L, (bb, t) in best_blocks.items()}}), flush=True)


if __name__ == "__main__":
    if "--_worker" not in sys.argv:
        from _supervise import supervise

        sys.exit(supervise(__file__, [a for a in sys.argv[1:]]))
    import jax

    if jax.default_backend() != "tpu":
        print(json.dumps({"error": "not on TPU", "backend": jax.default_backend()}))
        sys.exit(1)
    fails = check_numerics()
    bench()
    print(json.dumps({"numerics_failures": len(fails)}))
    sys.exit(1 if fails else 0)

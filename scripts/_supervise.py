"""Shared TPU-tunnel supervisor for the measurement scripts.

The remote-TPU tunnel in this environment is single-client and can wedge; a
wedged tunnel hangs ANY process at jax backend init.  ``supervise`` never
imports jax itself: it pre-probes the device in a timeboxed subprocess, then
runs the real measurement (``<script> --_worker ...``) under a watchdog, so
callers always get an error line instead of a hang (BENCH_NOTES.md "Tunnel
discipline").
"""

from __future__ import annotations

import json
import os
import subprocess
import sys


def supervise(script_file: str, argv, watchdog_seconds: int = 2400) -> int:
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=120,
        )
        if probe.returncode != 0:
            raise RuntimeError(
                (probe.stderr or "device probe failed").strip().splitlines()[-1][:200]
            )
    except (subprocess.TimeoutExpired, RuntimeError) as e:
        print(json.dumps({"error": f"device probe failed: {e}"[:250]}))
        return 1
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(script_file), "--_worker", *argv],
            text=True, timeout=watchdog_seconds,
        )
        return out.returncode
    except subprocess.TimeoutExpired:
        print(json.dumps({"error": f"timed out after {watchdog_seconds}s"}))
        return 1

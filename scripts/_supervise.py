"""Shared TPU-tunnel supervisor for the measurement scripts.

The remote-TPU tunnel in this environment is single-client and can wedge; a
wedged tunnel hangs ANY process at jax backend init.  ``supervise`` never
imports jax itself: it pre-probes the device in a timeboxed subprocess, then
runs the real measurement (``<script> --_worker ...``) under a watchdog, so
callers always get an error line instead of a hang (BENCH_NOTES.md "Tunnel
discipline").

Two watchdogs (review r4: a total-wall-clock kill rations healthy-but-slow
sessions, and killing an in-flight TPU client mid-stream is itself a wedge
trigger — so kill only on evidence of a hang):

- ``idle_seconds``: no worker stdout for this long means a hang (every
  measurement phase prints a JSON line when it completes); this is the
  primary kill.
- ``watchdog_seconds``: absolute backstop.

The worker's environment carries ``STOKE_SESSION_DEADLINE`` (epoch seconds
of the absolute backstop) so long-running workers can budget optional extra
phases (e.g. accuracy_run's f32 retry) against the REAL remaining time,
including when they run inside tpu_session's umbrella.
"""

from __future__ import annotations

import codecs
import json
import os
import selectors
import subprocess
import sys
import tempfile
import time

#: exit code of a worker killed by the stoke health watchdog — kept in sync
#: with stoke_tpu/telemetry/health.py WATCHDOG_EXIT_CODE (duplicated here
#: because this module must never import jax-importing packages)
HEALTH_WATCHDOG_EXIT_CODE = 113

#: exit code of a worker that was preempted and drained cleanly (emergency
#: checkpoint written) — kept in sync with stoke_tpu/resilience.py
#: PREEMPTION_EXIT_CODE.  Distinct from 113: the supervisor can tell
#: "drained, resume from the emergency tag" from "hung and self-killed".
PREEMPTION_EXIT_CODE = 114

#: env var the flight recorder appends bundle paths to (kept in sync with
#: stoke_tpu/telemetry/recorder.py BUNDLE_FILE_ENV)
BUNDLE_FILE_ENV = "STOKE_HEALTH_BUNDLE_FILE"


def _read_bundles(path: str) -> list[str]:
    """Bundle paths the worker's flight recorder reported (empty when no
    bundle was written or the handshake file is unreadable)."""
    try:
        with open(path) as f:
            return [ln.strip() for ln in f if ln.strip()]
    except OSError:
        return []


def supervise(
    script_file: str,
    argv,
    watchdog_seconds: int = 2400,
    idle_seconds: int | None = None,
) -> int:
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=120,
        )
        if probe.returncode != 0:
            raise RuntimeError(
                (probe.stderr or "device probe failed").strip().splitlines()[-1][:200]
            )
    except (subprocess.TimeoutExpired, RuntimeError) as e:
        print(json.dumps({"error": f"device probe failed: {e}"[:250]}))
        return 1
    deadline = time.time() + watchdog_seconds
    # health-bundle handshake: a worker running with HealthConfig appends
    # every post-mortem bundle path to this file, so a kill (ours or the
    # in-process hang watchdog's) still surfaces WHERE the corpse is
    bundle_fd, bundle_file = tempfile.mkstemp(prefix="stoke-bundles-")
    os.close(bundle_fd)
    env = {
        **os.environ,
        "STOKE_SESSION_DEADLINE": repr(deadline),
        BUNDLE_FILE_ENV: bundle_file,
    }
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(script_file), "--_worker", *argv],
        stdout=subprocess.PIPE,
        env=env,
    )
    # Non-blocking relay (ADVICE r4): a blocking readline() after select()
    # stalls until a full line arrives, so a worker wedging after a PARTIAL
    # line would disable both watchdogs.  os.read() on a non-blocking fd
    # always returns control to the watchdog loop.
    fd = proc.stdout.fileno()
    os.set_blocking(fd, False)
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    last_output = time.time()
    why = None
    eof = False
    # incremental decoder: a multi-byte UTF-8 char straddling a 64 KiB read
    # boundary must not decode to replacement chars mid-line
    decoder = codecs.getincrementaldecoder("utf-8")(errors="replace")

    def _relay() -> None:
        nonlocal last_output, eof
        while not eof:
            try:
                chunk = os.read(fd, 65536)
            except BlockingIOError:
                return
            except OSError:
                eof = True
                return
            if not chunk:
                # EOF with the worker possibly still alive (stdout closed/
                # redirected): unregister, or select() reports the dead fd
                # ready forever and this loop busy-spins until a watchdog
                eof = True
                sel.unregister(proc.stdout)
                sys.stdout.write(decoder.decode(b"", final=True))
                sys.stdout.flush()
                return
            sys.stdout.write(decoder.decode(chunk))
            sys.stdout.flush()
            last_output = time.time()

    try:
        while True:
            if eof:
                time.sleep(5)
            elif sel.select(timeout=5):
                _relay()
            if proc.poll() is not None:
                _relay()
                if proc.returncode == HEALTH_WATCHDOG_EXIT_CODE:
                    # the worker's in-process hang watchdog killed it: a
                    # distinct, diagnosable outcome (wedged collective /
                    # dead tunnel), with the post-mortem bundle attached
                    print(json.dumps({
                        "error": (
                            "worker killed by stoke health watchdog "
                            f"(exit {HEALTH_WATCHDOG_EXIT_CODE}: no step "
                            "completed within its timeout)"
                        ),
                        "watchdog_exit_code": HEALTH_WATCHDOG_EXIT_CODE,
                        "bundles": _read_bundles(bundle_file),
                    }))
                elif proc.returncode == PREEMPTION_EXIT_CODE:
                    # preempted and drained cleanly (ISSUE 7): the worker
                    # wrote an emergency checkpoint and exited resumably —
                    # scripts/run_resilient.py restarts these; here we
                    # surface the outcome so a bare supervise caller knows
                    # the run is resumable, not broken
                    print(json.dumps({
                        "error": (
                            "worker preempted and drained cleanly "
                            f"(exit {PREEMPTION_EXIT_CODE}: emergency "
                            "checkpoint written; resumable via "
                            "Stoke.resume() / scripts/run_resilient.py)"
                        ),
                        "preemption_exit_code": PREEMPTION_EXIT_CODE,
                        "resumable": True,
                        "bundles": _read_bundles(bundle_file),
                    }))
                return proc.returncode
            now = time.time()
            if now > deadline:
                why = f"timed out after {watchdog_seconds}s (absolute backstop)"
                break
            if idle_seconds and now - last_output > idle_seconds:
                why = (
                    f"no output for {idle_seconds}s (worker hung; killing is "
                    f"a known relay-wedge risk but the alternative is hanging "
                    f"forever)"
                )
                break
    finally:
        sel.close()
        bundles = _read_bundles(bundle_file)
        try:
            os.remove(bundle_file)
        except OSError:
            pass
    proc.kill()
    proc.wait()
    err = {"error": why}
    if bundles:
        # a post-mortem bundle beats a bare "timed out": point at it
        err["bundles"] = bundles
    print(json.dumps(err))
    return 1

"""Merge per-rank structured-trace files into one pod-wide Perfetto timeline.

The offline twin of the in-process trace view (ISSUE 10): a run with
``TraceConfig`` leaves one ``trace.rank<N>.json`` per process (chrome-trace
JSON, ``perf_counter``-clocked).  Those clocks share no epoch across hosts,
so a naive concat scatters the ranks along the time axis; this tool aligns
them by **step anchor** — the earliest optimizer step present in every
rank's events — shifting each rank's timeline so the anchor step's first
span starts at the same instant as rank 0's.  After the shift, per-rank
skew *within* a step is exactly what the merged timeline shows: the
straggler's long dispatch sits visibly past its peers' (the
``merge_rank_jsonl.py`` skew table, as a picture).

Usable on dead-run bundles: a flight-recorder ``trace.json`` (the span
ring at time of death) merges the same way — pass the bundle files
explicitly; files without a rank in their name are automatically
assigned the lowest indices no named ``trace.rank<N>.json`` claims.

Usage (CPU-safe; never imports jax, never touches an accelerator):

    python scripts/merge_rank_traces.py <dir-or-files...> [--out merged.json]
        [--anchor-step N] [--json]

``<dir>`` is scanned for ``trace.rank*.json``.  Two files parsing to the
same rank are refused (merging two hosts' rings into one rank would draw a
chimera timeline).  Exit 0 on a clean merge, 2 when nothing could be
aligned.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

_RANK_RE = re.compile(r"trace\.rank(\d+)\.json$")


def discover_traces(paths: List[str]) -> List[Tuple[int, str]]:
    """``[(rank, path), ...]`` from a mix of directories and files.

    Two files PARSING to the same rank raise — silently merging one
    host's ring into another's would place both hosts' spans on one
    process row and the skew picture would lie.  Unnamed files (a
    bundle's ``trace.json``) carry no rank claim and take the next free
    index."""
    named: List[Tuple[int, str]] = []
    unnamed: List[str] = []
    used: set = set()
    for p in paths:
        files = (
            sorted(glob.glob(os.path.join(p, "trace.rank*.json")))
            if os.path.isdir(p)
            else [p]
        )
        for f in files:
            m = _RANK_RE.search(os.path.basename(f))
            if m is None:
                unnamed.append(f)
                continue
            rank = int(m.group(1))
            if rank in used:
                raise ValueError(
                    f"{f}: rank {rank} already provided by another "
                    f"trace — merging two hosts' rings into one rank "
                    f"would draw a chimera timeline (pass one run's "
                    f"files at a time)"
                )
            used.add(rank)
            named.append((rank, f))
    # fallback indices only AFTER all named claims are collected: an
    # unnamed bundle trace listed before trace.rank0.json must not
    # squat on rank 0 and refuse the named file's legitimate claim
    out = list(named)
    fallback = 0
    for f in unnamed:
        while fallback in used:
            fallback += 1
        used.add(fallback)
        out.append((fallback, f))
    out.sort()
    return out


def load_events(path: str) -> List[Dict[str, Any]]:
    """The ``traceEvents`` list of one trace file (bare-list files — the
    chrome-trace array format — are accepted too)."""
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents") if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        # ValueError, not KeyError: main()'s salvage path catches this
        # and keeps merging the readable ranks
        raise ValueError(f"{path}: no traceEvents list")
    return events


def load_dropped(path: str) -> Optional[int]:
    """Spans the rank's bounded ring evicted before export, read from the
    exporter's ``stoke`` metadata block (ISSUE 16).  ``None`` for files
    that carry no metadata (bare-list chrome traces) — unknown is
    reported as unknown, never as zero: a truncated ring must not
    masquerade as a complete timeline."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    meta = doc.get("stoke")
    if not isinstance(meta, dict) or "dropped" not in meta:
        return None
    try:
        return int(meta["dropped"])
    except (TypeError, ValueError):
        return None


def _steps_present(events: List[Dict[str, Any]]) -> set:
    return {
        e["args"]["step"]
        for e in events
        if e.get("ph") == "X" and isinstance(e.get("args"), dict)
        and "step" in e["args"]
    }


def _anchor_ts(events: List[Dict[str, Any]], step: int) -> Optional[float]:
    """Earliest ``ts`` of a duration event tagged with ``step`` — the
    rank's anchor instant for the shift."""
    ts = [
        e["ts"]
        for e in events
        if e.get("ph") == "X" and isinstance(e.get("args"), dict)
        and e["args"].get("step") == step
    ]
    return min(ts) if ts else None


def merge_traces(
    traces: Dict[int, List[Dict[str, Any]]],
    anchor_step: Optional[int] = None,
) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Shift every rank's events so the anchor step's first span aligns
    with rank 0's (the lowest rank's, when 0 is absent); returns
    ``(merged_events, report)``.  Raises ValueError when no common step
    exists (or the requested anchor is missing from some rank)."""
    ranks = sorted(traces)
    if anchor_step is None:
        common = set.intersection(
            *(_steps_present(evs) for evs in traces.values())
        )
        # step 0 tags spans recorded before the first boundary (warm-up);
        # prefer a real optimizer-step anchor when one is common
        preferred = common - {0}
        if preferred:
            anchor_step = min(preferred)
        elif common:
            anchor_step = min(common)
        else:
            raise ValueError(
                "no optimizer step is present in every rank's trace; "
                "nothing to align on (pass --anchor-step to force one)"
            )
    anchors: Dict[int, float] = {}
    for rank in ranks:
        ts = _anchor_ts(traces[rank], anchor_step)
        if ts is None:
            raise ValueError(
                f"rank {rank} has no span tagged step {anchor_step}; "
                f"cannot align (its steps: "
                f"{sorted(_steps_present(traces[rank]))[:10]})"
            )
        anchors[rank] = ts
    base = anchors[ranks[0]]
    merged: List[Dict[str, Any]] = []
    shifts: Dict[int, float] = {}
    for rank in ranks:
        shift = base - anchors[rank]
        shifts[rank] = shift
        for e in traces[rank]:
            e = dict(e)
            e["pid"] = rank  # one Perfetto process row per rank
            if "ts" in e and e.get("ph") != "M":
                e["ts"] = e["ts"] + shift
            merged.append(e)
    merged.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    report = {
        "ranks": ranks,
        "anchor_step": anchor_step,
        "shift_us": {str(r): shifts[r] for r in ranks},
        "events": sum(
            1 for e in merged if e.get("ph") == "X"
        ),
    }
    return merged, report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="align per-rank trace.rank<N>.json files by step "
        "anchor into one Perfetto-loadable pod timeline"
    )
    ap.add_argument("paths", nargs="+",
                    help="trace output dir(s) or explicit trace files")
    ap.add_argument("--out", default="trace.merged.json",
                    help="merged chrome-trace output path")
    ap.add_argument("--anchor-step", type=int, default=None,
                    help="force the alignment step (default: the earliest "
                    "step present in every rank)")
    ap.add_argument("--json", action="store_true",
                    help="emit the merge report as one JSON document")
    args = ap.parse_args(argv)

    try:
        found = discover_traces(args.paths)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if not found:
        print("no trace*.json files found", file=sys.stderr)
        return 2
    traces: Dict[int, List[Dict[str, Any]]] = {}
    dropped: Dict[int, Optional[int]] = {}
    for rank, path in found:
        try:
            events = load_events(path)
        except (OSError, ValueError) as e:
            # dead-run salvage norm: report and keep merging what IS
            # readable (same policy as merge_rank_jsonl)
            print(f"skipping {path}: {e}", file=sys.stderr)
            continue
        if events:
            traces[rank] = events
            dropped[rank] = load_dropped(path)
    if not traces:
        print("no readable events in any trace", file=sys.stderr)
        return 2
    try:
        merged, report = merge_traces(traces, args.anchor_step)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    with open(args.out, "w") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f)
        f.write("\n")
    report["out"] = args.out
    # ring truncation surfaced beside the merge (ISSUE 16): each rank's
    # evicted-span count and the pod total — a nonzero total means the
    # merged timeline is the recent WINDOW, not the complete run, and
    # any critical-path read off it is partial
    report["dropped_by_rank"] = {str(r): dropped.get(r) for r in
                                 report["ranks"]}
    known = [d for d in dropped.values() if d is not None]
    report["trace/dropped_total"] = sum(known) if known else None
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(
            f"merged {report['events']} spans from ranks "
            f"{report['ranks']} (anchor step {report['anchor_step']}) "
            f"-> {args.out}"
        )
        for r in report["ranks"]:
            d = dropped.get(r)
            d_note = "dropped unknown" if d is None else f"dropped {d}"
            print(
                f"  rank {r}: shift {report['shift_us'][str(r)]:+.1f} us, "
                f"{d_note}"
            )
        total = report["trace/dropped_total"]
        if total:
            print(
                f"  WARNING: trace/dropped_total={total} — rings evicted "
                f"spans; the merged timeline is PARTIAL (recent window "
                f"only)"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Offline HLO capture of the fused ResNet-50 optimizer step (CPU lowering).

VERDICT r5 #4: the falls-with-batch anomaly (9,257 imgs/sec @ batch 256 →
7,786 @ 1024 on v5e) has an evidence kit (scripts/profile_capture.py) but
the one artifact it produced was never analyzed and artifacts/ is not
committed.  This script regenerates the evidence with NO tunnel: it lowers
and compiles the exact fused step the bench runs (bf16 policy, SGD momentum)
on the CPU backend at several batch sizes, writes the optimized HLO to
``artifacts/hlo_resnet50_cpu_bs<N>.txt.gz``, and prints the op-category
histogram per batch.

CPU-optimized HLO is NOT TPU-optimized HLO (different fusion/layout passes);
the op mix and op-count scaling with batch are still mechanical evidence for
the gap decomposition in BENCH_NOTES.md — convolution/reduce/fusion counts
are batch-invariant (the graph is the same program, only shapes change), so
what changes with batch is per-op shape efficiency, not schedule length.

Run:  JAX_PLATFORMS=cpu python scripts/hlo_dump.py --batches 16,256,1024
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)

from profile_capture import _hlo_histogram  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="16,256,1024")
    args = ap.parse_args()

    import jax
    import optax

    from stoke_tpu import Stoke, StokeOptimizer
    from stoke_tpu.engine import DeferredOutput
    from stoke_tpu.facade import is_deferred
    from stoke_tpu.models import ResNet50
    from stoke_tpu.utils import init_module

    artifacts = os.path.join(REPO, "artifacts")
    os.makedirs(artifacts, exist_ok=True)
    r = np.random.default_rng(0)
    model = ResNet50(num_classes=10, cifar_stem=True)
    variables = init_module(
        model, jax.random.PRNGKey(0), np.zeros((2, 32, 32, 3), np.float32),
        train=False,
    )

    for batch in (int(b) for b in args.batches.split(",")):
        stoke = Stoke(
            model=model,
            optimizer=StokeOptimizer(
                optimizer=optax.sgd,
                optimizer_kwargs={"learning_rate": 0.05, "momentum": 0.9},
            ),
            loss=lambda lo, la: (
                optax.softmax_cross_entropy_with_integer_labels(lo, la).mean()
            ),
            params=jax.tree_util.tree_map(lambda a: a.copy(), variables),
            batch_size_per_device=batch,
            device="cpu" if jax.default_backend() == "cpu" else "tpu",
            precision="bf16",
            model_train_kwargs={"train": True},
            model_eval_kwargs={"train": False},
            verbose=False,
        )
        x1 = jax.device_put(r.normal(size=(batch, 32, 32, 3)).astype(np.float32))
        y1 = jax.device_put(r.integers(0, 10, size=(batch,)))
        sentinel = DeferredOutput(None, -1)
        flat, treedef = jax.tree_util.tree_flatten(
            ((sentinel, y1), {}), is_leaf=is_deferred)
        arrays = stoke._place_batch([l for l in flat if not is_deferred(l)])
        dinfo = tuple((i, l._path) for i, l in enumerate(flat)
                      if is_deferred(l))
        fn = stoke._engine._build_fused(treedef, dinfo, True)
        # comm_state threads through the fused step on engines with the
        # gradient-transport layer; older snapshots lower without it
        extra = (
            (stoke._comm_state,) if hasattr(stoke, "_comm_state") else ()
        )
        compiled = fn.lower(
            stoke._variables, stoke._opt_state, stoke._grad_buf,
            stoke._scaler_state, *extra, stoke._rng,
            stoke._place_batch((x1,)), {}, arrays,
        ).compile()
        text = compiled.as_text()
        try:
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            flops = cost.get("flops")
        except Exception:
            flops = None
        hist = _hlo_histogram(text)
        path = os.path.join(artifacts, f"hlo_resnet50_cpu_bs{batch}.txt.gz")
        with gzip.open(path, "wt") as f:
            f.write(text)
        print(json.dumps({
            "probe": "hlo_dump_cpu", "batch": batch,
            "path": os.path.relpath(path, REPO),
            "gflops_per_step": None if flops is None else round(flops / 1e9, 2),
            **hist,
        }), flush=True)
        del stoke, compiled, text


if __name__ == "__main__":
    main()

"""stoke_lint: the repo's codified disciplines as a CLI (ISSUE 15).

One command, two halves:

- **Invariant linter** (default): the jax-free AST rules over the source
  tree — append-only wire formats against the committed manifest,
  config-knob status-rule coverage against the waiver file,
  nullable-JSONL schema discipline, and the banned-API rules
  (module-scope jax imports in jax-free modules — including THIS script
  — and ``device_get`` in engine/serving hot paths).
- **Program auditor** (``--programs``): builds a tiny live ``Stoke`` on
  the simulated CPU mesh in a SUBPROCESS, drives all four step APIs plus
  a serving engine, and runs ``Stoke.audit()`` over the lowered
  programs (donation integrity, hidden host round-trips, recompile
  hazards, sharding/collective accounting).

Usage (CI runs the default mode via ``make lint``):

    python scripts/stoke_lint.py                # lint the repo; exit 1 on findings
    python scripts/stoke_lint.py --json         # machine-readable findings
    python scripts/stoke_lint.py --programs     # + the live program audit (subprocess)

Exit codes: 0 clean, 1 findings, 2 usage/internal error.

Like ``scripts/autotune.py`` / ``scripts/run_resilient.py``, this
process NEVER imports jax (a wedged TPU tunnel hangs any process at
backend init — and CI lint must not depend on a backend at all): the
linter module is loaded from ``stoke_tpu/analysis/invariants.py`` by
FILE, bypassing the package ``__init__`` whose facade import would pull
jax in, and the program audit runs in a subprocess with a pinned CPU
environment.  The linter's own banned-API rule enforces this contract
on this very file.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
_INVARIANTS_PY = os.path.join(
    _REPO, "stoke_tpu", "analysis", "invariants.py"
)


def _load_invariants(repo_root: str):
    """Load the linter by FILE (never through the package __init__ —
    that imports the facade and therefore jax)."""
    path = os.path.join(repo_root, "stoke_tpu", "analysis", "invariants.py")
    if not os.path.exists(path):
        path = _INVARIANTS_PY
    spec = importlib.util.spec_from_file_location(
        "_stoke_analysis_invariants", path
    )
    mod = importlib.util.module_from_spec(spec)
    # dataclass field-type resolution looks the module up in sys.modules
    # — register before exec (the scripts/autotune.py discipline)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


#: the subprocess body for --programs: build a tiny Stoke on the 8-device
#: CPU mesh, drive all four step APIs + a serve engine, audit, and print
#: one JSON line of findings.  Runs under a PINNED environment so it can
#: never touch a real accelerator tunnel.
_PROGRAM_WORKER = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp
import optax
from stoke_tpu import Stoke

def model(p, x):
    return x @ p["w"]

def mse(o, y):
    return jnp.mean((o - y) ** 2)

def mk(**kw):
    return Stoke(model=model, optimizer=optax.sgd(0.1), loss=mse,
                 params={"w": np.zeros((8, 4), np.float32)},
                 batch_size_per_device=2, distributed="dp", verbose=False,
                 **kw)

rng = np.random.default_rng(0)
x = rng.normal(size=(16, 8)).astype(np.float32)
y = rng.normal(size=(16, 4)).astype(np.float32)

s = mk()
s.train_step(x, y)                                   # fused
out = s.model(x); s.backward(s.loss(out, y)); s.step()  # 4-call accum+apply
s2 = mk(grad_accum=2)
xs, ys = np.stack([x, x]), np.stack([y, y])
s2.train_step_window(xs, ys)                         # window
s2.train_steps(np.stack([xs, xs]), np.stack([ys, ys]))  # multi

# serving engine over a tiny GPT (the serve-program half)
from stoke_tpu.configs import ServeConfig
from stoke_tpu.models.gpt import GPT
from stoke_tpu.serving import ServingEngine
from stoke_tpu.utils import init_module
gpt = GPT(vocab_size=257, size_name="tiny", max_len=128, dropout_rate=0.0)
variables = init_module(gpt, jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32), train=False)
cfg = ServeConfig(max_seqs=2, kv_block_size=8, max_seq_len=64,
                  max_new_tokens=4, prefill_pad_multiple=16)
eng = ServingEngine(gpt, variables["params"], cfg)
eng.submit(np.array([5, 6, 7], np.int32))
eng.run()
# speculative + chunked engine (ISSUE 17): drives the serve_verify and
# serve_prefill_chunk_packed programs through the auditor too (s keeps
# the default engine, so the non-speculative serve_prefill/serve_decode
# programs stay covered)
spec_cfg = ServeConfig(max_seqs=2, kv_block_size=8, max_seq_len=64,
                       max_new_tokens=4, prefill_pad_multiple=16,
                       prefill_chunk_tokens=16, sampling=True,
                       speculative_k=3)
spec_eng = ServingEngine(gpt, variables["params"], spec_cfg)
spec_eng.submit(np.array([5, 9, 3] * 7, np.int32))  # 21 tokens -> 2 chunks
spec_eng.run()
# plain chunked engine (ISSUE 18): serve_prefill_chunk is the one serve
# program neither engine above dispatches (the speculative engine packs
# its chunks) — drive it so the cost manifest pins all five programs
chunk_cfg = ServeConfig(max_seqs=2, kv_block_size=8, max_seq_len=64,
                        max_new_tokens=4, prefill_pad_multiple=16,
                        prefill_chunk_tokens=16)
chunk_eng = ServingEngine(gpt, variables["params"], chunk_cfg)
chunk_eng.submit(np.array([5, 9, 3] * 7, np.int32))
chunk_eng.run()

# cost-drift gate (ISSUE 18): the committed analytic-cost manifest rides
# in via STOKE_COST_MANIFEST; the worker also reports every serve spec's
# measured cost so --update-costs can re-pin the manifest.  The
# memory-drift gate (ISSUE 19) mirrors it via STOKE_MEM_MANIFEST /
# --update-mem over memory_analysis temp/peak bytes.
import os
cost_manifest = None
manifest_path = os.environ.get("STOKE_COST_MANIFEST")
if manifest_path:
    with open(manifest_path) as fh:
        cost_manifest = json.load(fh)
mem_manifest = None
mem_path = os.environ.get("STOKE_MEM_MANIFEST")
if mem_path:
    with open(mem_path) as fh:
        mem_manifest = json.load(fh)

from stoke_tpu.analysis.program import (
    audit_program_specs,
    spec_cost_entry,
    spec_memory_entry,
)

findings = []
programs = []
notes = []
costs = {}
mems = {}
for st, serve_eng in ((s, eng), (s2, spec_eng)):
    before = st.dispatch_count
    rep = st.audit(serve=serve_eng, cost_manifest=cost_manifest,
                   mem_manifest=mem_manifest)
    assert st.dispatch_count == before, "audit dispatched a program"
    findings += [f.to_dict() for f in rep.findings]
    programs += rep.programs
    notes += rep.notes
# the chunked engine rides a standalone serve-spec audit (its step-side
# twin is already covered above)
rep = audit_program_specs(chunk_eng.audit_specs(),
                          cost_manifest=cost_manifest,
                          mem_manifest=mem_manifest)
findings += [f.to_dict() for f in rep.findings]
programs += rep.programs
# engines share programs (serve_decode is dispatched by two of them) —
# one defect, one finding
deduped, seen_f = [], set()
for f in findings:
    key = (f["rule"], f["file"], f["message"])
    if key not in seen_f:
        seen_f.add(key)
        deduped.append(f)
findings = deduped
for serve_eng in (eng, spec_eng, chunk_eng):
    for spec in serve_eng.audit_specs():
        if spec.program not in costs:
            entry = spec_cost_entry(spec)
            if entry is not None:
                costs[spec.program] = entry
        if spec.program not in mems:
            entry = spec_memory_entry(spec)
            if entry is not None:
                mems[spec.program] = entry
print(json.dumps({"programs": programs, "findings": findings,
                  "notes": notes, "costs": costs, "mems": mems}))
"""

#: the committed analytic-cost manifest the drift gate compares against
_COST_MANIFEST = os.path.join(
    "stoke_tpu", "analysis", "manifests", "program_costs.json"
)

#: the committed program-memory manifest (ISSUE 19) the memory-drift
#: gate compares against
_MEM_MANIFEST = os.path.join(
    "stoke_tpu", "analysis", "manifests", "program_memory.json"
)


def run_program_audit(
    repo_root: str,
    cost_manifest_path: str | None = None,
    mem_manifest_path: str | None = None,
) -> dict:
    """Spawn the jax-side program audit with a pinned CPU environment;
    returns the worker's JSON payload.  ``cost_manifest_path`` arms the
    audit-cost-drift gate and ``mem_manifest_path`` the
    audit-memory-drift gate (each defaults to its committed manifest
    when it exists; pass "" to disarm)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    if cost_manifest_path is None:
        default = os.path.join(repo_root, _COST_MANIFEST)
        cost_manifest_path = default if os.path.exists(default) else ""
    if cost_manifest_path:
        env["STOKE_COST_MANIFEST"] = os.path.abspath(cost_manifest_path)
    else:
        env.pop("STOKE_COST_MANIFEST", None)
    if mem_manifest_path is None:
        default = os.path.join(repo_root, _MEM_MANIFEST)
        mem_manifest_path = default if os.path.exists(default) else ""
    if mem_manifest_path:
        env["STOKE_MEM_MANIFEST"] = os.path.abspath(mem_manifest_path)
    else:
        env.pop("STOKE_MEM_MANIFEST", None)
    proc = subprocess.run(
        [sys.executable, "-c", _PROGRAM_WORKER],
        capture_output=True,
        text=True,
        env=env,
        cwd=repo_root,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"program-audit worker failed (exit {proc.returncode}):\n"
            f"{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="stoke_tpu invariant linter + program auditor"
    )
    ap.add_argument(
        "--repo-root",
        default=_REPO,
        help="tree to lint (default: this script's repo)",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="emit one JSON object instead of human-readable lines",
    )
    ap.add_argument(
        "--programs",
        action="store_true",
        help="also run the live program audit (subprocess, CPU mesh)",
    )
    ap.add_argument(
        "--cost-manifest",
        default=None,
        metavar="PATH",
        help="program-cost manifest for the audit-cost-drift gate "
        "(default: the committed "
        "stoke_tpu/analysis/manifests/program_costs.json; pass an "
        "empty string to disarm)",
    )
    ap.add_argument(
        "--update-costs",
        action="store_true",
        help="with --programs: rewrite the committed program-cost "
        "manifest from the live engines' measured analytic costs "
        "(run after an INTENTIONAL serve-program cost change)",
    )
    ap.add_argument(
        "--mem-manifest",
        default=None,
        metavar="PATH",
        help="program-memory manifest for the audit-memory-drift gate "
        "(default: the committed "
        "stoke_tpu/analysis/manifests/program_memory.json; pass an "
        "empty string to disarm)",
    )
    ap.add_argument(
        "--update-mem",
        action="store_true",
        help="with --programs: rewrite the committed program-memory "
        "manifest from the live engines' measured memory_analysis "
        "temp/peak bytes (run after an INTENTIONAL footprint change)",
    )
    args = ap.parse_args(argv)
    repo_root = os.path.abspath(args.repo_root)
    if not os.path.isdir(repo_root):
        print(f"stoke_lint: no such directory {repo_root!r}", file=sys.stderr)
        return 2

    if args.update_costs and not args.programs:
        print("stoke_lint: --update-costs requires --programs",
              file=sys.stderr)
        return 2
    if args.update_mem and not args.programs:
        print("stoke_lint: --update-mem requires --programs",
              file=sys.stderr)
        return 2

    inv = _load_invariants(repo_root)
    findings = [f.to_dict() for f in inv.run_invariant_lints(repo_root)]
    programs = []
    if args.programs:
        try:
            payload = run_program_audit(
                repo_root,
                # an update pass must MEASURE, not judge against the
                # stale pins it is about to replace
                cost_manifest_path="" if args.update_costs
                else args.cost_manifest,
                mem_manifest_path="" if args.update_mem
                else args.mem_manifest,
            )
        except Exception as e:
            print(f"stoke_lint: {e}", file=sys.stderr)
            return 2
        findings += payload["findings"]
        programs = payload["programs"]
        if args.update_costs:
            manifest_path = os.path.join(repo_root, _COST_MANIFEST)
            manifest = {
                "_comment": [
                    "ISSUE 18 analytic program-cost manifest: the",
                    "audit-cost-drift gate re-lowers every serve program",
                    "and compares its XLA cost analysis (FLOPs / bytes",
                    "accessed) against these pins at matching argument-",
                    "geometry signature.  Deviations beyond the tolerance",
                    "fail CI in BOTH directions (golden-file semantics).",
                    "Regenerate after an INTENTIONAL cost change with:",
                    "  python scripts/stoke_lint.py --programs --update-costs",
                ],
                "tolerance": 0.05,
                "programs": dict(sorted(payload["costs"].items())),
            }
            with open(manifest_path, "w") as fh:
                json.dump(manifest, fh, indent=2)
                fh.write("\n")
            print(
                f"stoke_lint: pinned {len(manifest['programs'])} "
                f"program cost(s) -> {manifest_path}"
            )
        if args.update_mem:
            manifest_path = os.path.join(repo_root, _MEM_MANIFEST)
            manifest = {
                "_comment": [
                    "ISSUE 19 program-memory manifest: the",
                    "audit-memory-drift gate re-compiles every serve",
                    "program and compares its memory_analysis temp/peak",
                    "bytes against these pins at matching argument-",
                    "geometry signature.  Deviations beyond the tolerance",
                    "fail CI in BOTH directions (golden-file semantics;",
                    "looser than the cost gate — XLA temp allocation",
                    "shifts more across versions than analytic FLOPs).",
                    "Regenerate after an INTENTIONAL footprint change:",
                    "  python scripts/stoke_lint.py --programs --update-mem",
                ],
                "tolerance": 0.25,
                "programs": dict(sorted(payload["mems"].items())),
            }
            with open(manifest_path, "w") as fh:
                json.dump(manifest, fh, indent=2)
                fh.write("\n")
            print(
                f"stoke_lint: pinned {len(manifest['programs'])} "
                f"program memory entr(y/ies) -> {manifest_path}"
            )

    if args.json:
        print(
            json.dumps(
                {
                    "version": inv.LINT_VERSION,
                    "findings": findings,
                    "programs_audited": programs,
                }
            )
        )
    else:
        for f in findings:
            print(
                f"{f['file']}:{f['line']}: [{f['rule']}] {f['message']} "
                f"— remedy: {f['remedy']}"
            )
        tail = f", {len(programs)} program(s) audited" if args.programs else ""
        print(f"stoke_lint: {len(findings)} finding(s){tail}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())

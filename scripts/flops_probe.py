"""Utilization anatomy for the CIFAR-10 ResNet-50 bench (gap analysis).

Measures, on one chip:
  1. bf16 matmul peak (8k^3) — the realistic MXU ceiling on this part
  2. ResNet-50 fwd-only (eval) step time
  3. full train_steps segment time (the bench path)
  4. XLA cost-model FLOPs of one fused optimizer step (facade
     estimate_step_flops)
and prints achieved TFLOP/s + fraction of measured peak per phase.

The point: if (3) tracks (4)/(1) closely and the 4call/train_step/
train_steps spread is small, the gap to the A100 constant is conv-shape
utilization (32x32 images, narrow channels), not framework overhead.

Run serialized on the TPU (supervised; tunnel is single-client).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _supervise import supervise  # noqa: E402


_SMOKE_RUN = False  # set from --smoke: smoke results must NEVER persist


def _mfu_fields(step_flops, step_seconds, peak_tflops):
    """Achieved TFLOP/s + fraction-of-peak via the shared CostCard
    arithmetic (stoke_tpu.telemetry.attribution.roofline_summary) — the
    same math the live attribution gauges use, instead of this script
    re-deriving ``flops / t / 1e12`` per arm (ISSUE 4 satellite).
    Returns None when the backend reported no FLOPs."""
    from stoke_tpu.telemetry.attribution import roofline_summary

    rl = roofline_summary(step_flops, step_seconds, peak_tflops)
    if rl["achieved_tflops"] is None:
        return None
    return {
        "achieved_tflops": round(rl["achieved_tflops"], 2),
        "fraction": round(rl["mfu"], 4),
    }


def _persist_mfu(metric: str, mfu, rec: dict, peak_tflops: float) -> None:
    """Record an on-chip MFU measurement in the shared BENCH_RESULTS.json
    ledger (VERDICT r3 item 3: MFU is the perf judging axis — a wedged
    tunnel in a later round must still be able to cite it).  Keep-best,
    accelerator-backed records only; never fails the probe run."""
    try:
        import time as _time

        import jax as _jax

        if _SMOKE_RUN or _jax.default_backend() == "cpu" or not mfu:
            return
        import bench

        bench.persist_result(
            metric,
            {
                "value": float(mfu),
                "unit": "mfu_vs_measured_matmul_peak",
                "vs_baseline": float(mfu),
                "date": _time.strftime("%Y-%m-%d"),
                "api": rec.get("probe"),
                "batch": rec.get("batch"),
                "backend": _jax.default_backend(),
                "peak_tflops": round(float(peak_tflops), 1),
                "achieved_tflops": rec.get("achieved_tflops"),
                "step_ms": rec.get("step_ms"),
                "source": "scripts/flops_probe.py fresh on-chip capture",
            },
            keep_best=True,
        )
    except Exception as e:  # ledger write must never fail the probe
        print(json.dumps({"ledger_error": str(e)[:120]}), flush=True)


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--_worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--gpt-size", default="base",
                    choices=["none", "tiny", "mini", "small", "medium",
                             "base", "large"],
                    help="compute-dense GPT phase size ('none' skips it)")
    ap.add_argument("--gpt-len", type=int, default=1024)
    ap.add_argument("--gpt-batch", type=int, default=8)
    ap.add_argument("--flash-len", type=int, default=4096,
                    help="sequence length of the flash+chunked-CE arm")
    ap.add_argument("--peak-n", type=int, default=8192,
                    help="matmul-peak probe size (shrink for CPU smokes)")
    ap.add_argument("--smoke", action="store_true",
                    help="CPU-safe flow validation: tiny shapes everywhere "
                    "(results are meaningless; nothing persists off-chip)")
    args = ap.parse_args()
    if args.smoke:
        global _SMOKE_RUN
        _SMOKE_RUN = True
        args.peak_n = min(args.peak_n, 512)
        args.gpt_size = "tiny"
        args.gpt_len = 128
        args.gpt_batch = 2
        args.flash_len = 256
    if not args._worker:
        sys.exit(supervise(__file__, sys.argv[1:]))

    import jax
    import jax.numpy as jnp
    import optax

    from stoke_tpu import Stoke, StokeOptimizer
    from stoke_tpu.models import ResNet50
    from stoke_tpu.utils import init_module

    from _timing import delta_time

    r = np.random.default_rng(0)

    # 1. matmul peak
    N = args.peak_n
    a = jax.device_put(jnp.asarray(r.normal(size=(N, N)).astype(np.float32),
                                   jnp.bfloat16))
    b = jax.device_put(jnp.asarray(r.normal(size=(N, N)).astype(np.float32),
                                   jnp.bfloat16))
    mm = jax.jit(lambda: (a @ b))
    t_mm = delta_time(mm, 10)
    peak_tflops = 2 * N**3 / t_mm / 1e12
    print(json.dumps({"probe": "matmul_peak", "n": N,
                      "ms": round(t_mm * 1e3, 3),
                      "tflops": round(peak_tflops, 1)}), flush=True)

    # 2-4. ResNet-50 through the facade (smoke: a narrow ResNet-18 — the
    # 50-layer compile alone takes minutes on one CPU core)
    batch, SEG = (16, 2) if args.smoke else (256, 10)
    if args.smoke:
        from stoke_tpu.models import ResNet18

        model = ResNet18(num_classes=10, num_filters=8, cifar_stem=True)
    else:
        model = ResNet50(num_classes=10, cifar_stem=True)
    variables = init_module(
        model, jax.random.PRNGKey(0), np.zeros((2, 32, 32, 3), np.float32),
        train=False,
    )
    stoke = Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.sgd,
            optimizer_kwargs={"learning_rate": 0.05, "momentum": 0.9},
        ),
        loss=lambda lo, la: optax.softmax_cross_entropy_with_integer_labels(
            lo, la).mean(),
        params=variables,
        batch_size_per_device=batch,
        device="tpu" if jax.default_backend() != "cpu" else "cpu",
        precision="bf16",
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        verbose=False,
    )
    x1 = jax.device_put(r.normal(size=(batch, 32, 32, 3)).astype(np.float32))
    y1 = jax.device_put(r.integers(0, 10, size=(batch,)))

    step_flops = stoke.estimate_step_flops(x1, (y1,))
    print(json.dumps({"probe": "cost_analysis",
                      "gflops_per_step": None if step_flops is None
                      else round(step_flops / 1e9, 1)}), flush=True)

    stoke.eval()
    t_fwd = delta_time(lambda: stoke.model(x1), 20)
    stoke.train()
    print(json.dumps({"probe": "fwd_only", "ms": round(t_fwd * 1e3, 3),
                      "imgs_per_sec": round(batch / t_fwd, 1)}), flush=True)

    xs = jax.device_put(r.normal(size=(SEG, batch, 32, 32, 3)).astype(np.float32))
    ys = jax.device_put(r.integers(0, 10, size=(SEG, batch)))
    t_seg = delta_time(lambda: stoke.train_steps(xs, (ys,)), 3)
    step_ms = t_seg / SEG * 1e3
    ips = batch * SEG / t_seg
    rec = {"probe": "train_steps", "step_ms": round(step_ms, 3),
           "batch": batch, "imgs_per_sec": round(ips, 1)}
    mf = _mfu_fields(step_flops, t_seg / SEG, peak_tflops)
    if mf:
        rec["achieved_tflops"] = mf["achieved_tflops"]
        rec["fraction_of_matmul_peak"] = mf["fraction"]
        _persist_mfu("cifar10_resnet50_bf16_train_mfu", rec
                     ["fraction_of_matmul_peak"], rec, peak_tflops)
    print(json.dumps(rec), flush=True)
    del stoke, xs, ys

    # 4b. ImageNet-shape ResNet-50 (224x224): the conv-utilization control.
    # Same model family as the headline bench but with spatial extents that
    # CAN tile the MXU — if ITS fraction-of-peak is healthy while the 32x32
    # run's is not, the CIFAR gap is conv shape, not the conv path itself.
    if not args.smoke:
        b224 = 64
        model224 = ResNet50(num_classes=1000, cifar_stem=False)
        v224 = init_module(
            model224, jax.random.PRNGKey(0),
            np.zeros((2, 224, 224, 3), np.float32), train=False,
        )
        s224 = Stoke(
            model=model224,
            optimizer=StokeOptimizer(
                optimizer=optax.sgd,
                optimizer_kwargs={"learning_rate": 0.05, "momentum": 0.9},
            ),
            loss=lambda lo, la: (
                optax.softmax_cross_entropy_with_integer_labels(lo, la).mean()
            ),
            params=v224,
            batch_size_per_device=b224,
            device="tpu" if jax.default_backend() != "cpu" else "cpu",
            precision="bf16",
            model_train_kwargs={"train": True},
            model_eval_kwargs={"train": False},
            verbose=False,
        )
        x224 = jax.device_put(
            r.normal(size=(b224, 224, 224, 3)).astype(np.float32))
        y224 = jax.device_put(r.integers(0, 1000, size=(b224,)))
        f224 = s224.estimate_step_flops(x224, (y224,))
        xs224 = jax.device_put(
            r.normal(size=(2, b224, 224, 224, 3)).astype(np.float32))
        ys224 = jax.device_put(r.integers(0, 1000, size=(2, b224)))
        t224 = delta_time(lambda: s224.train_steps(xs224, (ys224,)), 3)
        rec224 = {"probe": "resnet224", "batch": b224,
                  "step_ms": round(t224 / 2 * 1e3, 2),
                  "imgs_per_sec": round(b224 * 2 / t224, 1)}
        mf224 = _mfu_fields(f224, t224 / 2, peak_tflops)
        if mf224:
            rec224["achieved_tflops"] = mf224["achieved_tflops"]
            rec224["fraction_of_matmul_peak"] = mf224["fraction"]
            _persist_mfu("imagenet_resnet50_224_bf16_train_mfu",
                         rec224["fraction_of_matmul_peak"], rec224,
                         peak_tflops)
        print(json.dumps(rec224), flush=True)
        del s224, xs224, ys224, x224, y224, v224, model224

    # 5. compute-dense ceiling: GPT with MXU-shaped matmuls (hidden-width
    # GEMMs at seq 1k).  If THIS hits a healthy fraction of the measured
    # matmul peak while the 32x32 ResNet does not, the ResNet gap is
    # conv-shape utilization, not framework overhead — the round-2 gap
    # analysis keystone (BENCH_NOTES.md), now measured instead of argued.
    if args.gpt_size != "none":
        from stoke_tpu.models import causal_lm_loss
        from stoke_tpu.models.gpt import GPT

        L, gb, GSEG = args.gpt_len, args.gpt_batch, 4
        gpt = GPT(vocab_size=32768, size_name=args.gpt_size, max_len=L,
                  dropout_rate=0.0)
        gvars = init_module(
            gpt, jax.random.PRNGKey(0), np.zeros((2, L), np.int32),
            train=False,
        )
        gstoke = Stoke(
            model=gpt,
            optimizer=StokeOptimizer(
                optimizer=optax.adamw,
                optimizer_kwargs={"learning_rate": 3e-4},
            ),
            loss=causal_lm_loss,
            params=gvars,
            batch_size_per_device=gb,
            device="tpu" if jax.default_backend() != "cpu" else "cpu",
            precision="bf16",
            model_train_kwargs={"train": True},
            model_eval_kwargs={"train": False},
            verbose=False,
        )
        ids1 = jax.device_put(
            r.integers(0, 32768, size=(gb, L)).astype(np.int32))
        g_flops = gstoke.estimate_step_flops(ids1, (ids1,))
        gids = jax.device_put(
            r.integers(0, 32768, size=(GSEG, gb, L)).astype(np.int32))
        t_g = delta_time(lambda: gstoke.train_steps(gids, (gids,)), 3)
        grec = {"probe": "gpt_dense", "size": args.gpt_size, "L": L,
                "batch": gb,
                "step_ms": round(t_g / GSEG * 1e3, 2),
                "tok_per_sec": round(gb * L * GSEG / t_g, 1)}
        gmf = _mfu_fields(g_flops, t_g / GSEG, peak_tflops)
        if gmf:
            grec["achieved_tflops"] = gmf["achieved_tflops"]
            grec["mfu_vs_matmul_peak"] = gmf["fraction"]
            _persist_mfu(f"gpt_{args.gpt_size}_bf16_train_mfu",
                         grec["mfu_vs_matmul_peak"], grec, peak_tflops)
        print(json.dumps(grec), flush=True)
        del gstoke, gids

        # 6. long-context composition: flash attention + chunked LM-head CE
        # at L=4k, vocab 32k (VERDICT r3 item 3's "flash + chunked-CE" GPT
        # arm) — the realistic long-context train configuration whose MFU
        # belongs in the ledger next to the dense arm
        from stoke_tpu.ops import chunked_causal_lm_loss, make_flash_attention

        Lf = args.flash_len
        fb = max(1, args.gpt_batch // 4)
        gptf = GPT(vocab_size=32768, size_name=args.gpt_size, max_len=Lf,
                   dropout_rate=0.0, chunked_head=True,
                   attention_fn=make_flash_attention(causal=True),
                   attention_is_causal=True)
        fvars = init_module(
            gptf, jax.random.PRNGKey(0), np.zeros((2, Lf), np.int32),
            train=False,
        )
        fstoke = Stoke(
            model=gptf,
            optimizer=StokeOptimizer(
                optimizer=optax.adamw,
                optimizer_kwargs={"learning_rate": 3e-4},
            ),
            loss=lambda out, ids: chunked_causal_lm_loss(out, ids, chunk=512),
            params=fvars,
            batch_size_per_device=fb,
            device="tpu" if jax.default_backend() != "cpu" else "cpu",
            precision="bf16",
            model_train_kwargs={"train": True},
            model_eval_kwargs={"train": False},
            verbose=False,
        )
        fids1 = jax.device_put(
            r.integers(0, 32768, size=(fb, Lf)).astype(np.int32))
        f_flops = fstoke.estimate_step_flops(fids1, (fids1,))
        fids = jax.device_put(
            r.integers(0, 32768, size=(2, fb, Lf)).astype(np.int32))
        t_f = delta_time(lambda: fstoke.train_steps(fids, (fids,)), 3)
        frec = {"probe": "gpt_flash_chunked", "size": args.gpt_size,
                "L": Lf, "batch": fb,
                "step_ms": round(t_f / 2 * 1e3, 2),
                "tok_per_sec": round(fb * Lf * 2 / t_f, 1)}
        fmf = _mfu_fields(f_flops, t_f / 2, peak_tflops)
        if fmf:
            frec["achieved_tflops"] = fmf["achieved_tflops"]
            frec["mfu_vs_matmul_peak"] = fmf["fraction"]
            _persist_mfu(
                f"gpt_{args.gpt_size}_flash4k_chunkedce_train_mfu",
                frec["mfu_vs_matmul_peak"], frec, peak_tflops)
        print(json.dumps(frec), flush=True)


if __name__ == "__main__":
    main()

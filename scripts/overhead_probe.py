"""Framework-overhead isolation: Stoke facade vs hand-written JAX train step.

Times CIFAR-10 ResNet-50 bf16 training two ways on the same chip with the
same delta-timing rig as bench.py:
  1. `stoke.train_steps` (the framework's fastest path)
  2. a minimal hand-rolled jitted train step (flax apply + optax sgd, bf16
     casts inline, donated state) — the "no framework" ceiling
Prints one JSON line per variant; the ratio is the facade overhead.  Run
serially on the TPU (tunnel is single-client; supervised like bench.py).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _supervise import supervise  # noqa: E402


def main():
    if "--_worker" not in sys.argv:
        sys.exit(supervise(__file__, sys.argv[1:]))

    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--_worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--seg", type=int, default=10)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from stoke_tpu import Stoke, StokeOptimizer
    from stoke_tpu.models import ResNet50
    from stoke_tpu.utils import init_module

    batch, SEG = args.batch, args.seg
    r = np.random.default_rng(0)
    model = ResNet50(num_classes=10, cifar_stem=True)
    variables = init_module(
        model, jax.random.PRNGKey(0), np.zeros((2, 32, 32, 3), np.float32),
        train=False,
    )

    def timed(fn, state, xs, ys, reps):
        t0 = time.perf_counter()
        out = None
        for _ in range(reps):
            state, out = fn(state, xs, ys)
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        return time.perf_counter() - t0, state

    xs = jax.device_put(r.normal(size=(SEG, batch, 32, 32, 3)).astype(np.float32))
    ys = jax.device_put(r.integers(0, 10, size=(SEG, batch)))

    # ---- variant 1: facade train_steps ---------------------------------- #
    stoke = Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.sgd,
            optimizer_kwargs={"learning_rate": 0.05, "momentum": 0.9},
        ),
        loss=lambda lo, la: optax.softmax_cross_entropy_with_integer_labels(
            lo, la).mean(),
        params=variables,
        batch_size_per_device=batch,
        device="tpu" if jax.default_backend() != "cpu" else "cpu",
        precision="bf16",
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        verbose=False,
    )

    def facade_step(state, xs, ys):
        return state, stoke.train_steps(xs, (ys,))

    timed(facade_step, None, xs, ys, 1)  # compile
    t1, _ = timed(facade_step, None, xs, ys, 3)
    t2, _ = timed(facade_step, None, xs, ys, 6)
    ips = batch * 3 * SEG / max(t2 - t1, 1e-9)
    print(json.dumps({"variant": "facade_train_steps",
                      "imgs_per_sec": round(ips, 1)}), flush=True)
    del stoke

    # ---- variant 2: minimal hand-rolled JAX ----------------------------- #
    tx = optax.sgd(0.05, momentum=0.9)
    params = variables["params"]
    bstats = variables.get("batch_stats", {})
    opt = tx.init(params)

    def loss_fn(p, bs, x, y):
        out, upd = model.apply(
            {"params": p, "batch_stats": bs},
            x.astype(jnp.bfloat16), train=True, mutable=["batch_stats"],
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            out.astype(jnp.float32), y).mean(), upd["batch_stats"]

    def one(state, xy):
        p, bs, opt = state
        x, y = xy
        (l, bs), g = jax.value_and_grad(loss_fn, has_aux=True)(p, bs, x, y)
        up, opt = tx.update(g, opt, p)
        return (optax.apply_updates(p, up), bs, opt), l

    @jax.jit
    def raw_steps(state, xs, ys):
        state, ls = jax.lax.scan(lambda s, xy: one(s, xy), state, (xs, ys))
        return state, ls[-1]

    state = (params, bstats, opt)
    _, state = timed(raw_steps, state, xs, ys, 1)  # compile
    t1, state = timed(raw_steps, state, xs, ys, 3)
    t2, state = timed(raw_steps, state, xs, ys, 6)
    ips_raw = batch * 3 * SEG / max(t2 - t1, 1e-9)
    print(json.dumps({"variant": "raw_jax_scan",
                      "imgs_per_sec": round(ips_raw, 1)}), flush=True)
    print(json.dumps({"facade_fraction_of_raw":
                      round(ips / max(ips_raw, 1e-9), 3)}), flush=True)


if __name__ == "__main__":
    main()

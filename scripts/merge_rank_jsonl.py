"""Merge/align per-rank telemetry JSONL streams into a per-host skew table.

The offline twin of the in-band fleet view (ISSUE 5): a run with
``TelemetryConfig(jsonl_all_ranks=True)`` leaves one ``steps.rank<N>.jsonl``
per process; this tool aligns them by optimizer step and prints, per logged
window, each host's wall time / loader wait / dispatch time skew vs the
fleet median plus a straggler verdict — the same math
(``stoke_tpu.telemetry.fleet.straggler_verdict``) the live exchange runs,
usable on bundles salvaged from DEAD runs where the in-band view never got
to report.

Usage (CPU-safe; never touches an accelerator):

    env PYTHONPATH=. JAX_PLATFORMS=cpu \
        python scripts/merge_rank_jsonl.py <dir-or-files...> [--json]
        [--rel-threshold 0.25] [--zscore 3.0] [--no-validate]

``<dir>`` is scanned for ``steps.rank*.jsonl``; explicit file paths are
taken as one stream per rank (rank parsed from the name, else positional).
Exit 0 when streams merged cleanly, 2 when nothing could be aligned.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_RANK_RE = re.compile(r"steps\.rank(\d+)\.jsonl$")


def discover_streams(paths: List[str]) -> List[Tuple[int, str]]:
    """``[(rank, path), ...]`` from a mix of directories and files.

    Two files PARSING to the same rank (e.g. two runs' ``steps.rank0.
    jsonl``) raise — silently extending one host's stream with another's
    would compute the skew table over a chimera host.  Unnamed files
    (``steps.jsonl``) carry no rank claim and are assigned the next free
    index; mixing files from different runs is on the caller."""
    out: List[Tuple[int, str]] = []
    used: set = set()
    fallback = 0
    for p in paths:
        files = (
            sorted(glob.glob(os.path.join(p, "steps.rank*.jsonl")))
            if os.path.isdir(p)
            else [p]
        )
        if os.path.isdir(p) and not files:
            # single-rank runs write steps.jsonl — still mergeable (a
            # fleet of one, skew table degenerates to a timeline)
            solo = os.path.join(p, "steps.jsonl")
            if os.path.exists(solo):
                files = [solo]
        for f in files:
            m = _RANK_RE.search(os.path.basename(f))
            if m:
                rank = int(m.group(1))
                if rank in used:
                    raise ValueError(
                        f"{f}: rank {rank} already provided by another "
                        f"stream — merging two hosts' files into one "
                        f"rank would corrupt the skew table (pass one "
                        f"run's files at a time)"
                    )
            else:
                while fallback in used:
                    fallback += 1
                rank = fallback
            used.add(rank)
            out.append((rank, f))
    out.sort()
    return out


def load_stream(path: str, validate: bool) -> List[Dict[str, Any]]:
    from stoke_tpu.telemetry.events import read_step_events

    return read_step_events(path, validate=validate)


def align_by_step(
    streams: Dict[int, List[Dict[str, Any]]],
) -> List[Tuple[int, Dict[int, Dict[str, Any]]]]:
    """``[(step, {rank: record})]`` for steps present in EVERY stream —
    a rank missing a step (crashed mid-window, clock-skewed cadence) is
    reported, not silently padded."""
    by_rank_step = {
        rank: {r["step"]: r for r in recs} for rank, recs in streams.items()
    }
    common = set.intersection(*(set(d) for d in by_rank_step.values()))
    return [
        (step, {rank: by_rank_step[rank][step] for rank in by_rank_step})
        for step in sorted(common)
    ]


def window_matrix(
    rows: Dict[int, Dict[str, Any]],
    prev: Optional[Dict[int, Dict[str, Any]]],
) -> "Any":
    """Per-host fleet matrix for one aligned window.  Wall time is the ts
    delta to the rank's previous aligned record (the live view's window
    wall); barrier wait is not in the step events, so that column is zero
    and the verdict runs on wall + loader skew alone."""
    import numpy as np

    from stoke_tpu.telemetry.fleet import FLEET_INDEX, N_FLEET_SIGNALS

    ranks = sorted(rows)
    m = np.zeros((len(ranks), N_FLEET_SIGNALS), np.float64)
    for i, rank in enumerate(ranks):
        r = rows[rank]
        m[i, FLEET_INDEX["step"]] = r["step"]
        if prev is not None and rank in prev:
            m[i, FLEET_INDEX["wall_s"]] = max(
                r["ts"] - prev[rank]["ts"], 0.0
            )
            # compile_time_s in step events is run-cumulative; the wire
            # format's compile_s slot is per-window — delta like wall
            m[i, FLEET_INDEX["compile_s"]] = max(
                (r.get("compile_time_s") or 0.0)
                - (prev[rank].get("compile_time_s") or 0.0),
                0.0,
            )
        m[i, FLEET_INDEX["loader_wait_s"]] = r.get("loader_wait_s") or 0.0
        m[i, FLEET_INDEX["comm_bytes_onwire"]] = (
            r.get("comm_bytes_onwire") or 0.0
        )
        m[i, FLEET_INDEX["health_anomalies"]] = (
            r.get("health_anomalies") or 0.0
        )
    return m


def merge(
    streams: Dict[int, List[Dict[str, Any]]],
    rel_threshold: float,
    zscore: float,
) -> Dict[str, Any]:
    """The full offline fleet report: one verdict row per aligned window
    (the first window has no wall baseline and is skipped), plus per-host
    cumulative totals and the modal straggler."""
    from stoke_tpu.telemetry.fleet import straggler_verdict

    aligned = align_by_step(streams)
    ranks = sorted(streams)
    windows: List[Dict[str, Any]] = []
    prev: Optional[Dict[int, Dict[str, Any]]] = None
    for step, rows in aligned:
        if prev is not None:
            matrix = window_matrix(rows, prev)
            v = straggler_verdict(
                matrix, rel_threshold=rel_threshold,
                zscore_threshold=zscore,
            )
            v["step"] = step
            # map matrix row index back to the actual rank id
            v["host"] = ranks[v["host"]]
            if v["barrier_charged_host"] is not None:
                v["barrier_charged_host"] = ranks[v["barrier_charged_host"]]
            windows.append(v)
        prev = rows
    totals = {
        rank: {
            "records": len(recs),
            "loader_wait_s": sum(r.get("loader_wait_s") or 0.0 for r in recs),
            "host_dispatch_s": sum(
                r.get("host_dispatch_s") or 0.0 for r in recs
            ),
            "compile_time_s": (
                (recs[-1].get("compile_time_s") or 0.0) if recs else 0.0
            ),
        }
        for rank, recs in streams.items()
    }
    flagged = [w for w in windows if w["flagged"]]
    modal = None
    if flagged:
        counts: Dict[int, int] = {}
        for w in flagged:
            counts[w["host"]] = counts.get(w["host"], 0) + 1
        modal = max(counts, key=counts.get)
    return {
        "hosts": ranks,
        "aligned_windows": len(windows),
        "unaligned_steps": {
            rank: len(recs) - len(aligned)
            for rank, recs in streams.items()
        },
        "windows": windows,
        "per_host_totals": totals,
        "flagged_windows": len(flagged),
        "modal_straggler": modal,
    }


def print_table(report: Dict[str, Any]) -> None:
    hdr = (
        f"{'step':>8} {'hosts':>5} {'wall_med':>9} {'wall_max':>9} "
        f"{'lag_s':>8} {'lag%':>6} {'straggler':>9} {'class':>8}"
    )
    print(hdr)
    print("-" * len(hdr))
    for w in report["windows"]:
        print(
            f"{w['step']:>8} {w['hosts']:>5} "
            f"{w['wall_median_s']:>9.3f} {w['wall_max_s']:>9.3f} "
            f"{w['lag_s']:>8.3f} {100 * w['lag_frac']:>5.1f}% "
            f"{(str(w['host']) if w['flagged'] else '-'):>9} "
            f"{w['skew_class']:>8}"
        )
    print()
    print(
        f"{report['aligned_windows']} aligned windows across "
        f"{len(report['hosts'])} hosts; {report['flagged_windows']} flagged"
        + (
            f"; modal straggler: host {report['modal_straggler']}"
            if report["modal_straggler"] is not None
            else ""
        )
    )
    for rank, t in sorted(report["per_host_totals"].items()):
        print(
            f"  host {rank}: {t['records']} records, "
            f"loader_wait {t['loader_wait_s']:.3f}s, "
            f"dispatch {t['host_dispatch_s']:.3f}s, "
            f"compile {t['compile_time_s']:.3f}s"
        )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="align per-rank steps.rank<N>.jsonl streams into a "
        "per-host skew table (the offline fleet view)"
    )
    ap.add_argument("paths", nargs="+",
                    help="telemetry output dir(s) or explicit jsonl files")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as one JSON document")
    ap.add_argument("--rel-threshold", type=float, default=0.25,
                    help="lag/median-wall fraction flagging a straggler")
    ap.add_argument("--zscore", type=float, default=3.0,
                    help="cross-host lag z-score flagging a straggler")
    ap.add_argument("--no-validate", action="store_true",
                    help="skip step-event schema validation (salvaging "
                    "truncated streams from dead runs)")
    args = ap.parse_args(argv)

    try:
        found = discover_streams(args.paths)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if not found:
        print("no steps*.jsonl streams found", file=sys.stderr)
        return 2
    streams: Dict[int, List[Dict[str, Any]]] = {}
    for rank, path in found:
        try:
            recs = load_stream(path, validate=not args.no_validate)
        except (OSError, ValueError) as e:
            # typo'd/deleted/unreadable paths are the dead-run-salvage
            # norm: report and keep merging what IS readable
            print(f"skipping {path}: {e}", file=sys.stderr)
            continue
        if recs:
            streams.setdefault(rank, []).extend(recs)
    if not streams:
        print("no readable records in any stream", file=sys.stderr)
        return 2
    report = merge(streams, args.rel_threshold, args.zscore)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print_table(report)
    if report["aligned_windows"] == 0:
        # streams loaded but share no steps (disjoint cadences, or one
        # truncated before the other began) — "nothing could be aligned"
        # is the documented nonzero-exit condition
        print(
            "no step is present in every stream; nothing aligned",
            file=sys.stderr,
        )
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Telemetry smoke: ONE CPU train step with the full pipeline enabled.

Proves the observability stack end-to-end in seconds (``make
telemetry-smoke``): a JSONL step record (schema-validated on read-back)
carrying the health-sentinel fields, a Prometheus exposition file, a TB
event stream readable by the native frame parser, and — since ISSUE 3 — a
forced post-mortem bundle with the flight-recorder ring, all-thread
stacks, and run config.  Since ISSUE 6, one compile-cache warm start;
since ISSUE 7, one preemption → emergency-save → resume cycle (manifest
written, counters restored); since ISSUE 8, one sharded-transport step
(int8 reduce-scatter under sddp: param-gather bytes + compression in the
JSONL); since ISSUE 9, one serve cycle (two concurrent requests through
the continuous-batching paged-KV engine with int8 weights: TTFT/TPOT
fields in the JSONL, >= 3.5x compression asserted, blocks drained back
to the pool); since ISSUE 10, one traced train window + one traced serve
request (the exported trace.rank0.json files must parse as chrome-trace
JSON and carry engine step spans AND a full per-request
admission->prefill->decode timeline); since ISSUE 12, a per-layer
numerics window (per-group JSONL block, a NaN injected into a known
layer attributed to that group's index in record + anomaly, and an
offline numerics_diff.py alignment of two smoke JSONLs); since ISSUE 13,
the serve cycle additionally runs one chunked-prefill + top-p request
(chunk/sampled counters in the JSONL, ``serve/prefill_chunk`` spans
asserted in the traced timeline; ``--serve-only`` runs just that leg —
the ``make serve-smoke`` entry); since ISSUE 16, one SLO-tagged request
(serve/slo_* JSONL fields, attainment in the summary block, and the
span-walked violation attribution whose buckets sum to the measured
end-to-end latency); since ISSUE 17, the serve cycle runs speculative
(``speculative_k=3``) with one repetitive-prompt request the
prompt-lookup drafter accelerates — accept-rate > 0 asserted on the
serve/spec_* counters, and the greedy streams asserted BIT-IDENTICAL to
a non-speculative reference engine; since ISSUE 19, the train window and
the serve cycle both run memory-armed (``MemoryConfig``) — the ``mem/*``
JSONL ledger block asserted to recombine exactly (components sum to the
resident total), the serve record carrying the KV headroom forecast and
the engine-side ledger (quantized weight store + KV block pool), the
ledger gauges in the Prometheus exposition, and every NON-armed run's
records asserted memory-free (the default-OFF contract); since ISSUE 20,
the train window and the serve cycle both run with a live ops plane
(``OpsPlaneConfig(port=0)``) — all six endpoints polled over real HTTP
(``/metrics``, ``/healthz``, ``/statusz`` asserted to be EXACTLY the
pinned ``STATUSZ_FIELDS`` tuple, ``/requests`` showing the serve
cycle's queued table, ``/trace``, and a bounded ``/profile`` capture
riding the attribution budget), plus a halting run proving the
``/healthz`` 200→503 drain flip on an injected-NaN halt.  Prints the
step record and a one-line verdict; exit 0 only when everything
round-trips.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _trace_events(path):
    with open(path) as f:
        doc = json.load(f)
    return [e for e in doc["traceEvents"] if e.get("ph") == "X"]


def _ops_get(base, path):
    """One real-HTTP GET against the live ops plane (ISSUE 20): returns
    ``(status, body_text)`` — error statuses are data here, not
    exceptions (a scraper reads 503 as the drain verdict)."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(base + path, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def run_serve_cycle(sv_dir: str) -> dict:
    """One traced serve cycle end-to-end (ISSUE 9, grown by 13 and 16):
    two concurrent greedy requests PLUS one long chunked-prefill + top-p
    request PLUS one SLO-tagged request through the continuous-batching
    engine (int8 weights), with the serve/* JSONL fields populated
    (compression >= 3.5x, prefill-chunk and sampled-token counters, the
    nullable serve/slo_* attainment fields), every KV block back in the
    pool after the drain, the per-request span timelines — including the
    ``serve/prefill_chunk`` chunk spans — asserted in the exported
    trace, and the SLO request's span-walked attribution summing to its
    end-to-end latency.  Since ISSUE 17 the engine is speculative
    (``speculative_k=3``): a repetitive-prompt request exercises the
    prompt-lookup drafter + k-token verify program (accept-rate > 0 on
    the serve/spec_* counters), and every greedy stream is asserted
    bit-identical to a non-speculative reference engine — the
    speculative default-correctness contract.  Callable standalone
    (``--serve-only``, the ``make serve-smoke`` leg) or as part of the
    full smoke."""
    import numpy as np
    import optax

    import jax as _jx

    from stoke_tpu import (
        AttributionConfig,
        MemoryConfig,
        OpsPlaneConfig,
        ServeConfig,
        Stoke,
        StokeOptimizer,
        TelemetryConfig,
        TraceConfig,
    )
    from stoke_tpu.models.gpt import GPT
    from stoke_tpu.serving import RequestSLO, SamplingParams, ServingEngine
    from stoke_tpu.telemetry import read_step_events
    from stoke_tpu.utils import init_module

    sv_model = GPT(
        vocab_size=211, size_name="tiny", max_len=128, dropout_rate=0.0
    )
    sv_vars = init_module(
        sv_model, _jx.random.PRNGKey(0), np.zeros((1, 8), np.int32),
        train=False,
    )
    sv = Stoke(
        model=sv_model,
        optimizer=StokeOptimizer(
            optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.1}
        ),
        loss=lambda o, y: 0.0,
        params=sv_vars,
        batch_size_per_device=1,
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        configs=[
            TelemetryConfig(
                output_dir=sv_dir, log_every_n_steps=1, prometheus=True,
                tensorboard=False, sample_device_time=False, track_hbm=False,
            ),
            ServeConfig(
                max_seqs=2, kv_block_size=8, max_seq_len=64,
                max_new_tokens=4, prefill_pad_multiple=16,
                quant="int8", quant_min_size=256,
                # ISSUE 13: chunked prefill + sampling-aware programs
                # (the two short requests stay greedy — temperature 0)
                prefill_chunk_tokens=16, sampling=True,
                # ISSUE 17: self-drafting speculative decode — every
                # decode iteration is a k-token verify dispatch; greedy
                # streams stay bit-identical (asserted below)
                speculative_k=3,
                # ISSUE 18: the serve roofline observatory — cost cards
                # at the dispatch funnel, the serve/cost_* JSONL block,
                # and the verify-over-decode intensity uplift (asserted
                # below; the AttributionConfig supplies the v5e peaks)
                cost_cards=True,
            ),
            AttributionConfig(peak_tflops=197.0, peak_hbm_gbps=819.0),
            # ISSUE 19: the serve-side HBM ledger — the engine registers
            # its quantized weight store + KV block pool, the serve
            # records carry the mem/* block and the KV headroom
            # forecast, and the recombination identity is asserted below
            MemoryConfig(),
            # traced serve requests (ISSUE 10/13): the per-request
            # admission -> [chunks] -> prefill -> decode timelines are
            # parsed below
            TraceConfig(output_dir=os.path.join(sv_dir, "trace")),
            # live ops plane (ISSUE 20): the serve cycle is scrapeable
            # over real HTTP while it runs — ephemeral port, loopback
            OpsPlaneConfig(port=0),
        ],
        verbose=False,
    )
    sv_eng = sv.serve()
    sv_r = np.random.default_rng(0)
    sv_rids = [
        sv_eng.submit(sv_r.integers(1, 211, size=7).astype(np.int32), 4)
        for _ in range(2)
    ]
    # ISSUE 13: one long prompt (40 > 16 tokens -> 3 chunks) served with
    # top-p sampling from a pinned seed
    long_rid = sv_eng.submit(
        sv_r.integers(1, 211, size=40).astype(np.int32), 4,
        sampling=SamplingParams(temperature=0.7, top_p=0.9, seed=1),
    )
    # ISSUE 16: one SLO-tagged request — deadlines generous enough that a
    # CPU smoke attains them deterministically; the serve/slo_* JSONL
    # fields, the summary block, and the span-walked violation
    # attribution are asserted below
    slo_rid = sv_eng.submit(
        sv_r.integers(1, 211, size=9).astype(np.int32), 4,
        slo=RequestSLO(priority="interactive",
                       ttft_target_s=60.0, tpot_target_s=60.0),
    )
    # ISSUE 17: one repetitive-prompt greedy request — the workload
    # prompt-lookup drafting exists for (the tiled trigram repeats, so
    # the drafter proposes the continuation and the verify program
    # accepts it; accept-rate > 0 asserted below)
    spec_prompt = np.asarray([5, 9, 3] * 4, np.int32)
    spec_rid = sv_eng.submit(spec_prompt, 8)
    # live ops plane (ISSUE 20): five requests submitted, engine not yet
    # run — the /requests table shows every one QUEUED, the SLO-tagged
    # request carrying its remaining TTFT headroom (the drain/admission
    # signal an operator reads before deciding where to send load)
    op_base = f"http://127.0.0.1:{sv.opsplane.port}"
    _, op_body = _ops_get(op_base, "/requests")
    op_queued = json.loads(op_body)["requests"]
    sv_eng.run()
    # greedy-identity reference (ISSUE 17): the same greedy prompts
    # through a NON-speculative engine (same model / int8 weights — the
    # quantizer is seed-deterministic) must yield bit-identical streams;
    # exact-match verification makes speculation a pure dispatch-count
    # optimization
    ref_eng = ServingEngine(
        sv_model, sv_vars["params"],
        ServeConfig(
            max_seqs=2, kv_block_size=8, max_seq_len=64,
            max_new_tokens=4, prefill_pad_multiple=16,
            quant="int8", quant_min_size=256,
            prefill_chunk_tokens=16, sampling=True,
        ),
    )
    ref_r = np.random.default_rng(0)
    ref_prompts = [
        ref_r.integers(1, 211, size=7).astype(np.int32) for _ in range(2)
    ]
    ref_rids = [ref_eng.submit(p, 4) for p in ref_prompts]
    ref_spec_rid = ref_eng.submit(spec_prompt, 8)
    ref_eng.run()
    greedy_identity = all(
        list(sv_eng.scheduler.finished[a].tokens)
        == list(ref_eng.scheduler.finished[b].tokens)
        for a, b in list(zip(sv_rids, ref_rids))
        + [(spec_rid, ref_spec_rid)]
    )
    # live ops plane (ISSUE 20), post-drain: /statusz carries the full
    # engine summary block (completed counts, occupancy back to zero)
    _, op_body = _ops_get(op_base, "/statusz")
    op_statusz = json.loads(op_body)
    opsplane_ok = (
        len(op_queued) == 5
        and all(r["state"] == "queued" for r in op_queued)
        and any(
            r["rid"] == slo_rid
            and r["priority"] == "interactive"
            and (r["slo_headroom_s"] or 0) > 0
            for r in op_queued
        )
        and (op_statusz.get("serving") or {}).get("completed") == 5
        and (op_statusz.get("serving") or {}).get("kv_blocks_used") == 0
    )
    sv.close_telemetry()
    sv_rec = read_step_events(os.path.join(sv_dir, "steps.jsonl"))[-1]
    sv_prom = open(os.path.join(sv_dir, "metrics.prom")).read()
    serve_trace = _trace_events(
        os.path.join(sv_dir, "trace", "trace.rank0.json")
    )
    spans_by_rid = {}
    for e in serve_trace:
        rid = (e.get("args") or {}).get("request_id")
        if rid is not None:
            spans_by_rid.setdefault(rid, set()).add(e["name"])
    chunk_spans = [
        e for e in serve_trace if e["name"] == "serve/prefill_chunk"
    ]
    # ISSUE 16: the SLO-tagged request's attainment and span-walked
    # attribution — buckets must sum to the measured end-to-end latency,
    # with full span coverage (the cycle runs traced)
    slo_attr = sv_eng.slo.attributions.get(slo_rid, {})
    slo_summary = sv_eng.summary().get("slo", {})
    slo_bucket_sum = (
        slo_attr.get("queue_wait_s", 0.0)
        + slo_attr.get("prefill_blocked_s", 0.0)
        + slo_attr.get("decode_contention_s", 0.0)
    )
    spec_drafted = sv_rec.get("serve/spec_draft_tokens") or 0.0
    spec_accepted = sv_rec.get("serve/spec_accepted_tokens") or 0.0
    # ISSUE 18: the cost-card block and the roofline summary — analytic
    # FLOPs/bytes accumulated at the dispatch funnel, decode-family
    # classified memory-bound at the v5e peaks, and the verify program's
    # intensity uplift over plain decode > 1 (the reference engine runs
    # without cost_cards, so its summary block must stay inactive)
    cost_summary = sv_eng.summary().get("cost", {})
    cost_ok = (
        (sv_rec.get("serve/cost_flops") or 0.0) > 0
        and (sv_rec.get("serve/cost_bytes") or 0.0) > 0
        and sv_rec.get("serve/cost_decode_bound") == "memory"
        and (sv_rec.get("serve/cost_attainable_tpot_s") or 0.0) > 0
        and sv_rec.get("serve/cost_flops_per_token") is not None
        and cost_summary.get("active") is True
        and (cost_summary.get("verify_intensity_uplift") or 0.0) > 1.0
        and ref_eng.summary().get("cost", {}).get("active") is False
    )
    # ISSUE 19: the serve-side HBM ledger — the engine's two components
    # (quantized weight store + KV block pool) recombine EXACTLY into
    # the resident total on the JSONL record, the train-only components
    # stay None (absent, not zero), per-program memory_analysis peaks
    # were captured at the dispatch funnel, the KV headroom forecast
    # rides the serve block (every block back in the pool => the full
    # free-pool bytes), the ledger gauges reach the exposition, and the
    # memory-free reference engine's summary block stays inactive (the
    # default-OFF contract, engine-side)
    mem_summary = sv_eng.summary().get("memory", {})
    mem_ok = (
        (sv_rec.get("mem/params_bytes") or 0) > 0
        and (sv_rec.get("mem/kv_cache_bytes") or 0) > 0
        and sv_rec.get("mem/params_bytes")
        + sv_rec.get("mem/kv_cache_bytes")
        == sv_rec.get("mem/resident_bytes")
        and sv_rec.get("mem/opt_state_bytes") is None
        and sv_rec.get("mem/transport_bytes") is None
        and (sv_rec.get("mem/temp_peak_bytes") or 0) > 0
        and (sv_rec.get("serve/mem_headroom_bytes") or 0) > 0
        and "stoke_mem_resident_bytes" in sv_prom
        and "stoke_serve_mem_headroom_bytes" in sv_prom
        and mem_summary.get("active") is True
        and bool(mem_summary.get("programs"))
        and "serve" in mem_summary.get("preflights", {})
        and ref_eng.summary().get("memory", {}).get("active") is False
    )
    ok = (
        all(
            len(sv_eng.scheduler.finished[rid].tokens) == 4
            for rid in sv_rids + [long_rid, slo_rid]
        )
        and len(sv_eng.scheduler.finished[spec_rid].tokens) == 8
        and sv_rec.get("serve/completed") == 5.0
        and sv_rec.get("serve/ttft_p50_s") is not None
        and sv_rec.get("serve/tpot_p50_s") is not None
        and (sv_rec.get("serve/quant_compression") or 0) >= 3.5
        and sv_rec.get("serve/kv_block_occupancy") == 0.0
        and sv_eng.allocator.used_blocks == 0
        and "stoke_serve_ttft_s" in sv_prom
        and "stoke_serve_kv_block_occupancy" in sv_prom
        # ISSUE 13: the chunked + sampled request's wire evidence — the
        # counters in the JSONL record and the chunk spans in the traced
        # serve cycle (40 prompt tokens over 16-token chunks = 3)
        and sv_rec.get("serve/prefill_chunks") == 3.0
        and sv_rec.get("serve/sampled_tokens") == 4.0
        and len(chunk_spans) == 3
        and {"serve/prefill_chunk", "serve/decode"}
        <= spans_by_rid.get(long_rid, set())
        # ISSUE 16: SLO wire evidence — the nullable serve/slo_* fields
        # in the JSONL record, attainment in the summary block, and the
        # attribution identity queue+prefill+decode == e2e
        and sv_rec.get("serve/slo_requests") == 1.0
        and sv_rec.get("serve/slo_attainment") == 1.0
        and sv_rec.get("serve/slo_goodput_tokens_per_s") is not None
        and slo_attr.get("attained") is True
        and slo_attr.get("span_coverage") == "full"
        and slo_attr.get("partial") is False
        and abs(slo_bucket_sum - slo_attr.get("e2e_s", -1.0)) < 1e-9
        and slo_summary.get("by_class", {})
        .get("interactive", {}).get("attained") == 1
        # ISSUE 17: speculative wire evidence — drafts scored AND
        # accepted (accept-rate > 0), acceptance never exceeding the
        # drafted count, and the greedy streams bit-identical to the
        # non-speculative reference engine
        and spec_drafted > 0
        and 0 < spec_accepted <= spec_drafted
        and greedy_identity
        # ISSUE 18: cost-card / roofline wire evidence
        and cost_ok
        and "stoke_serve_cost_flops_total" in sv_prom
        # ISSUE 19: HBM-ledger wire evidence
        and mem_ok
        # ISSUE 20: the in-flight request table and the post-drain
        # engine summary, both read over real HTTP
        and opsplane_ok
    )
    return {
        "ok": ok,
        "opsplane_ok": opsplane_ok,
        "opsplane_queued": len(op_queued),
        "mem_ok": mem_ok,
        "mem_summary": mem_summary,
        "cost_summary": cost_summary,
        "spec_drafted": spec_drafted,
        "spec_accepted": spec_accepted,
        "spec_accept_rate": (
            spec_accepted / spec_drafted if spec_drafted else 0.0
        ),
        "greedy_identity": greedy_identity,
        "spec_rid": spec_rid,
        "spec_tokens": list(sv_eng.scheduler.finished[spec_rid].tokens),
        "record": sv_rec,
        "engine": sv_eng,
        "prom": sv_prom,
        "trace_events": serve_trace,
        "spans_by_rid": spans_by_rid,
        "chunk_spans": len(chunk_spans),
        "long_rid": long_rid,
        "long_tokens": list(sv_eng.scheduler.finished[long_rid].tokens),
        "slo_rid": slo_rid,
        "slo_attribution": slo_attr,
        "slo_summary": slo_summary,
    }


def main() -> int:
    import numpy as np
    import optax

    from stoke_tpu import (
        AttributionConfig,
        FleetConfig,
        HealthConfig,
        HealthHaltError,
        MemoryConfig,
        NumericsConfig,
        OpsPlaneConfig,
        ProfilerConfig,
        Stoke,
        StokeOptimizer,
        TelemetryConfig,
        TraceConfig,
    )
    from stoke_tpu.telemetry.opsplane import STATUSZ_FIELDS
    from stoke_tpu.telemetry import read_step_events
    from stoke_tpu.utils.tb_writer import read_scalar_events

    out_dir = os.environ.get(
        "STOKE_TELEMETRY_SMOKE_DIR",
        tempfile.mkdtemp(prefix="stoke-telemetry-smoke-"),
    )
    cfg = TelemetryConfig(
        output_dir=out_dir,
        log_every_n_steps=1,
        tensorboard=True,
        grad_norm=True,
    )
    hcfg = HealthConfig(dump_signals=False)
    # step-time attribution (ISSUE 4): one window through the CostCard /
    # MFU / goodput path on CPU — peak is arbitrary here, only the
    # plumbing is being proven
    acfg = AttributionConfig(peak_tflops=1.0, peak_hbm_gbps=100.0)
    # fleet view (ISSUE 5): one exchange window end-to-end — a fleet of
    # one host on CPU, proving the packed-vector/aggregation/JSONL path
    fcfg = FleetConfig(window_steps=1)
    # structured tracing (ISSUE 10): the span ring records the train
    # window below; the exported trace.rank0.json is parsed at the end
    tr_dir = os.path.join(out_dir, "trace")
    trcfg = TraceConfig(output_dir=tr_dir, ring_size=512)
    # per-layer numerics (ISSUE 12): the group-stats matrix rides the
    # same compiled step; the per-group block is asserted on the record
    nmcfg = NumericsConfig()
    # HBM capacity ledger (ISSUE 19): the analytic per-subsystem
    # observatory rides the same window — the mem/* JSONL block, the
    # recombination identity, and the ledger gauges are asserted below
    mmcfg = MemoryConfig()
    # live ops plane (ISSUE 20): the run is scrapeable WHILE it trains —
    # all six endpoints are polled over real HTTP below; port 0 binds an
    # ephemeral loopback port, and the ProfilerConfig trace_dir gives
    # /profile somewhere to land its bounded manual capture
    opcfg = OpsPlaneConfig(port=0)
    pfcfg = ProfilerConfig(trace_dir=os.path.join(out_dir, "xprof"))
    stoke = Stoke(
        model=lambda p, x: x @ p["w"],
        optimizer=StokeOptimizer(
            optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.1}
        ),
        loss=lambda o, y: ((o - y) ** 2).mean(),
        params={"w": np.ones((8, 4), np.float32)},
        batch_size_per_device=16,
        configs=[cfg, hcfg, acfg, fcfg, trcfg, nmcfg, mmcfg, opcfg, pfcfg],
        verbose=False,
    )
    x = np.ones((16, 8), np.float32)
    y = np.zeros((16, 4), np.float32)
    stoke.train_step(x, (y,))
    # second step: the fleet view anchors its cadence on the first record
    # (warm-up discard) and closes its first exchange window on the next
    stoke.train_step(x, (y,))
    # live ops plane (ISSUE 20): all six endpoints polled over real HTTP
    # while the run is still alive — the exposition carries the same
    # families the sink file gets at close, /statusz is EXACTLY the
    # pinned field tuple (absent subsystems null, serving included: no
    # engine in this run), /trace serves the live span ring, and
    # /profile lands a bounded manual capture riding (and burning) the
    # attribution capture budget
    ops_base = f"http://127.0.0.1:{stoke.opsplane.port}"
    _, ops_metrics = _ops_get(ops_base, "/metrics")
    ops_hz_status, ops_hz_body = _ops_get(ops_base, "/healthz")
    _, ops_statusz_body = _ops_get(ops_base, "/statusz")
    _, ops_requests_body = _ops_get(ops_base, "/requests")
    _, ops_trace_body = _ops_get(ops_base, "/trace")
    ops_pf_status, ops_pf_body = _ops_get(ops_base, "/profile?seconds=0.05")
    ops_statusz = json.loads(ops_statusz_body)
    ops_profile = json.loads(ops_pf_body)
    opsplane_train_ok = (
        "stoke_jax_compiles_total" in ops_metrics
        and ops_hz_status == 200
        and json.loads(ops_hz_body)["ok"] is True
        and tuple(ops_statusz) == STATUSZ_FIELDS
        and ops_statusz["serving"] is None
        and (ops_statusz["training"] or {}).get("goodput") is not None
        and json.loads(ops_requests_body)["requests"] == []
        and any(
            e.get("name") == "stoke/dispatch"
            for e in json.loads(ops_trace_body)
        )
        and ops_pf_status == 200
        and os.path.isdir(ops_profile["trace_dir"])
    )
    # forced post-mortem dump: the bundle a human reads after a crash —
    # exercised end-to-end so the crash path is proven BEFORE the crash
    bundle = stoke.health.dump("smoke")
    stoke.close_telemetry()

    # the /healthz 200→503 flip (ISSUE 20): a second armed run halts on
    # an injected NaN — and the plane keeps serving AFTER the halt (the
    # socket is the load-balancer drain signal; it must not die with the
    # step loop)
    hz_dir = os.path.join(out_dir, "opsplane_halt")
    hz_stoke = Stoke(
        model=lambda p, x: x @ p["w"],
        optimizer=StokeOptimizer(
            optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.1}
        ),
        loss=lambda o, y: ((o - y) ** 2).mean(),
        params={"w": np.ones((8, 4), np.float32)},
        batch_size_per_device=16,
        configs=[
            TelemetryConfig(
                output_dir=hz_dir, log_every_n_steps=1, prometheus=False,
                tensorboard=False, sample_device_time=False, track_hbm=False,
            ),
            HealthConfig(nonfinite_action="halt", dump_signals=False),
            OpsPlaneConfig(port=0),
        ],
        verbose=False,
    )
    hz_base = f"http://127.0.0.1:{hz_stoke.opsplane.port}"
    hz_stoke.train_step(x, (y,))
    hz_before, _ = _ops_get(hz_base, "/healthz")
    xn = x.copy()
    xn[:, 3] = np.nan
    hz_halted = False
    try:
        hz_stoke.train_step(xn, (y,))
    except HealthHaltError:
        hz_halted = True
    hz_after, hz_after_body = _ops_get(hz_base, "/healthz")
    hz_verdict = json.loads(hz_after_body)
    hz_stoke.close_telemetry()
    opsplane_flip_ok = (
        hz_before == 200
        and hz_halted
        and hz_after == 503
        and hz_verdict["ok"] is False
        and hz_verdict["halted"] == "nonfinite_grads"
        and (hz_verdict["anomalies"] or 0) >= 1
    )

    # persistent compile cache (ISSUE 6): one cached warm-start
    # end-to-end — a cold construction misses and persists, a second
    # construction hits the ledger, and the step outputs are
    # bit-identical between the two
    from stoke_tpu import CompileConfig

    cc_dir = os.path.join(out_dir, "compile_cache")

    def _cc_run():
        s = Stoke(
            model=lambda p, x: x @ p["w"],
            optimizer=StokeOptimizer(
                optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.1}
            ),
            loss=lambda o, y: ((o - y) ** 2).mean(),
            params={"w": np.full((8, 4), 0.5, np.float32)},
            batch_size_per_device=16,
            configs=[CompileConfig(cache_dir=cc_dir)],
            verbose=False,
        )
        s.train_step(x, (y,))
        return s

    cc_cold = _cc_run()
    cc_warm = _cc_run()
    compile_cache_ok = (
        cc_cold.compile_cache.misses >= 1
        and cc_warm.compile_cache.hits >= 1
        and cc_warm.compile_cache.saved_compile_s > 0
        and np.array_equal(
            np.asarray(cc_cold.params["w"]), np.asarray(cc_warm.params["w"])
        )
    )

    # pod-scale resilience (ISSUE 7): one preemption -> emergency-save ->
    # resume cycle end-to-end — the in-process variant (exit_on_preempt
    # False raises PreemptedError instead of exiting), proving the
    # manifest-verified resume restores step counters AND the
    # out-of-payload state (rng/EMA) bit-identically
    from stoke_tpu import PreemptedError, ResilienceConfig

    rz_root = os.path.join(out_dir, "resilience")
    rz_cfg = ResilienceConfig(save_path=rz_root, exit_on_preempt=False)

    def _rz_run():
        return Stoke(
            model=lambda p, x: x @ p["w"],
            optimizer=StokeOptimizer(
                optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.1}
            ),
            loss=lambda o, y: ((o - y) ** 2).mean(),
            params={"w": np.full((8, 4), 2.0, np.float32)},
            batch_size_per_device=16,
            configs=[rz_cfg],
            verbose=False,
        )

    rz_first = _rz_run()
    rz_first.train_step(x, (y,))
    rz_first.resilience.request_preemption("smoke")
    preempted = False
    try:
        rz_first.train_step(x, (y,))  # boundary honors the notice
    except PreemptedError:
        preempted = True
    rz_resumed = _rz_run()
    resumed_ok = rz_resumed.resume()
    rz_resumed.train_step(x, (y,))  # the step the preempted run never ran
    resilience_ok = (
        preempted
        and resumed_ok
        and rz_resumed.optimizer_steps == 3
        and (rz_resumed.resilience_summary or {}).get("resumed_step") == 2
        and os.path.exists(
            os.path.join(
                rz_root, "stoke-emergency-backward-step-2", "manifest.json"
            )
        )
    )
    rz_first.close_telemetry()
    rz_resumed.close_telemetry()

    # elastic resilience (ISSUE 14): one OFFLOAD-STAGED async save →
    # topology-elastic resume cycle — the save stages device→host off the
    # step path (no main-thread gather) onto the 8-device mesh, and a
    # 4-device run restores it bit-identically with the elastic counter
    # ticking
    import jax as _jax

    from stoke_tpu import CheckpointConfig, MeshConfig

    el_root = os.path.join(out_dir, "elastic")
    el_ckpt = CheckpointConfig(async_save=True, offload_staging=True)

    def _el_run(mesh_cfg=None):
        cfgs = [
            el_ckpt,
            ResilienceConfig(
                save_path=el_root, exit_on_preempt=False
            ),
        ]
        if mesh_cfg is not None:
            cfgs.append(mesh_cfg)
        return Stoke(
            model=lambda p, x: x @ p["w"],
            optimizer=StokeOptimizer(
                optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.1}
            ),
            loss=lambda o, y: ((o - y) ** 2).mean(),
            params={"w": np.full((8, 4), 2.0, np.float32)},
            batch_size_per_device=2,
            distributed="dp",
            configs=cfgs,
            verbose=False,
        )

    el_first = _el_run()
    el_first.train_step(x, (y,))
    el_first._save_with_config(el_root, "emergency", el_ckpt, None)
    el_first.wait_for_checkpoint()
    el_params = np.asarray(el_first.params["w"])
    el_half = _el_run(
        MeshConfig(devices=np.array(_jax.devices("cpu")[:4]))
    )
    el_resumed = el_half.resume()
    el_sum = el_half.resilience_summary or {}
    elastic_ok = (
        el_resumed
        and int(el_first._mesh.size) == 8
        and int(el_half._mesh.size) == 4
        and np.array_equal(np.asarray(el_half.params["w"]), el_params)
        and el_sum.get("elastic_resumes") == 1
        and os.path.exists(
            os.path.join(
                el_root,
                "stoke-emergency-backward-step-1",
                "variables.staged.rank0.npz",
            )
        )
    )
    el_first.close_telemetry()
    el_half.close_telemetry()

    # sharded quantized transport (ISSUE 8): one optimizer step through
    # the weight-update-sharded path — int8 reduce-scatter + per-shard
    # error feedback under sddp — with the JSONL recording BOTH wire legs
    # (grad compression >= 3.5x analytic, param all-gather bytes) and the
    # residual carried as per-replica partitions
    from stoke_tpu import CommConfig, OSSConfig, SDDPConfig
    from stoke_tpu.parallel.zero import ShardedGradTransport

    import jax as _jax

    world = len(_jax.devices("cpu"))
    zr_dir = os.path.join(out_dir, "zero")
    zr = Stoke(
        model=lambda p, x: x @ p["w"],
        optimizer=StokeOptimizer(
            optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.1}
        ),
        loss=lambda o, y: ((o - y) ** 2).mean(),
        params={"w": np.ones((8, 4), np.float32)},
        batch_size_per_device=2,
        distributed="dp",
        oss=True,
        sddp=True,
        configs=[
            CommConfig(dtype="int8", chunk_elems=32, bucket_mb=0.001),
            OSSConfig(min_shard_size=1),
            SDDPConfig(min_shard_size=1),
            TelemetryConfig(
                output_dir=zr_dir, log_every_n_steps=1, prometheus=False,
                tensorboard=False, sample_device_time=False, track_hbm=False,
            ),
        ],
        verbose=False,
    )
    zx = np.ones((2 * world, 8), np.float32)
    zy = np.zeros((zx.shape[0], 4), np.float32)
    zr.train_step(zx, (zy,))
    zr.close_telemetry()
    zero_rec = read_step_events(os.path.join(zr_dir, "steps.jsonl"))[-1]
    zero_sharded = isinstance(zr._engine.transport, ShardedGradTransport)
    zero_ok = (
        zero_sharded
        and (
            world == 1  # 1-wide mesh moves nothing on the wire
            or (
                (zero_rec.get("comm_compression") or 0) >= 3.5
                and (zero_rec.get("comm_bytes_param_gather") or 0) > 0
            )
        )
        and "residual" in zr._comm_state
    )

    # serving stack (ISSUE 9 + 13): one serve cycle end-to-end
    sv_dir = os.path.join(out_dir, "serve")
    sv_result = run_serve_cycle(sv_dir)
    serving_ok = sv_result["ok"]
    sv_rec = sv_result["record"]
    sv_eng = sv_result["engine"]

    # per-layer numerics observatory (ISSUE 12): two runs of a TWO-group
    # model — one clean, one with a NaN injected into the SECOND layer's
    # gradients only (the loss is separable, so lay_a's gradients stay
    # finite) — asserting the per-group JSONL block, a non-empty summary,
    # the NaN attributed to lay_b's group index in record AND anomaly,
    # and an offline numerics_diff.py alignment of the two JSONLs
    import subprocess

    nm_a_dir = os.path.join(out_dir, "numerics_a")
    nm_b_dir = os.path.join(out_dir, "numerics_b")

    def _nm_run(nm_dir, inject_nan):
        s = Stoke(
            model=lambda p, x: (p["lay_a"]["w"] * x[:, :4, None]).sum()
            + (p["lay_b"]["w"] * x[:, 4:, None]).sum(),
            optimizer=StokeOptimizer(
                optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.0}
            ),
            loss=lambda o: o,
            params={
                "lay_a": {"w": np.ones((4, 3), np.float32)},
                "lay_b": {"w": np.ones((4, 3), np.float32)},
            },
            batch_size_per_device=8,
            configs=[
                TelemetryConfig(
                    output_dir=nm_dir, log_every_n_steps=1,
                    prometheus=False, tensorboard=False,
                    sample_device_time=False, track_hbm=False,
                ),
                HealthConfig(dump_signals=False),
                NumericsConfig(),
            ],
            verbose=False,
        )
        nx = np.ones((8, 8), np.float32)
        s.train_step(nx, ())
        nx2 = nx.copy()
        if inject_nan:
            nx2[:, 5] = np.nan  # only lay_b's gradient sees it
        s.train_step(nx2, ())
        s.close_telemetry()
        return s

    nm_clean = _nm_run(nm_a_dir, inject_nan=False)
    nm_nan = _nm_run(nm_b_dir, inject_nan=True)
    nm_rec = read_step_events(os.path.join(nm_b_dir, "steps.jsonl"))[-1]
    nm_clean_rec = read_step_events(
        os.path.join(nm_a_dir, "steps.jsonl")
    )[-1]
    nm_summary = nm_nan.numerics_summary or {}
    nm_anomalies = {
        a.detector for a in (nm_nan.health.anomalies if nm_nan.health else [])
    }
    diff_proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "numerics_diff.py"),
         nm_a_dir, nm_b_dir, "--json", "--stat", "update_rms"],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        diff_report = json.loads(diff_proc.stdout)
    except ValueError:
        diff_report = {}
    numerics_ok = (
        (nm_rec.get("numerics/per_group") or {}).keys()
        == {"lay_a", "lay_b"}
        and nm_rec.get("numerics/provenance_group") == 1
        and nm_rec.get("numerics/provenance_name") == "lay_b"
        and nm_rec.get("numerics/provenance_field") == "grad"
        and nm_clean_rec.get("numerics/provenance_group") is None
        and "numerics_provenance" in nm_anomalies
        and bool(nm_summary.get("top_grad_noise"))
        and diff_proc.returncode == 0
        and diff_report.get("aligned_steps", 0) >= 2
        and set(diff_report.get("groups") or []) == {"lay_a", "lay_b"}
    )

    # structured tracing (ISSUE 10): both exported traces must parse as
    # chrome-trace JSON; the train trace must carry engine step spans,
    # the serve trace at least one full request timeline — admission,
    # prefill, and decode spans sharing one request_id (the serve cycle
    # already parsed its own trace, chunk spans included)
    train_trace = _trace_events(os.path.join(tr_dir, "trace.rank0.json"))
    serve_trace = sv_result["trace_events"]
    step_span_names = {e["name"] for e in train_trace}
    spans_by_rid = sv_result["spans_by_rid"]
    tracing_ok = (
        bool(step_span_names & {"stoke/dispatch", "stoke/accum", "stoke/step"})
        and "stoke/place" in step_span_names
        and sum(
            1
            for names in spans_by_rid.values()
            if {"serve/admission", "serve/prefill", "serve/decode"} <= names
        ) >= 2
        and (stoke.trace_summary or {}).get("spans", 0) > 0
    )

    records = read_step_events(os.path.join(out_dir, "steps.jsonl"))
    print(json.dumps(records[-1], sort_keys=True))
    rec = records[-1]
    # the read_step_events round-trip already schema-validated the record;
    # additionally require the ISSUE 3 sentinel fields to be POPULATED
    health_fields_ok = (
        rec.get("grad_norm") is not None
        and rec.get("param_norm") is not None
        and rec.get("update_ratio") is not None
        and rec.get("nonfinite_leaves") == 0.0
        and rec.get("health_anomalies") == 0.0
    )
    # ISSUE 4: the attribution window populated MFU + bound + a goodput
    # partition, and the end-of-run goodput summary is coherent
    goodput = stoke.goodput or {}
    attribution_ok = (
        rec.get("mfu") is not None
        and rec.get("achieved_tflops") is not None
        and rec.get("bound") in ("compute", "memory", "comm", "host")
        and rec.get("goodput_productive_s") is not None
        and goodput.get("windows", 0) >= 1
        and goodput.get("goodput_fraction") is not None
    )
    # ISSUE 5: the fleet window populated the per-host view (a fleet of
    # one here: skew zero, class "none") and the end-of-run summary
    fleet = stoke.fleet_summary or {}
    fleet_ok = (
        rec.get("fleet/hosts") == 1
        and rec.get("fleet/window", 0) >= 1
        and rec.get("fleet/skew_class") == "none"
        and fleet.get("windows", 0) >= 1
    )
    bundle_files = set(os.listdir(bundle)) if os.path.isdir(bundle) else set()
    bundle_ok = {
        "manifest.json", "ring.jsonl", "config.json", "mesh.json",
        "environment.json", "stacks.txt",
        # ISSUE 4: utilization at time of death rides every bundle
        "goodput.json", "cost_cards.json",
        # ISSUE 5: which host was slow at time of death
        "fleet.json",
        # ISSUE 10: what the host was doing at time of death
        "trace.json",
        # ISSUE 12: which layer was bad at time of death
        "numerics.json",
    } <= bundle_files
    ring_kinds = set()
    if bundle_ok:
        with open(os.path.join(bundle, "ring.jsonl")) as f:
            ring_kinds = {json.loads(ln)["kind"] for ln in f if ln.strip()}
    prom = open(os.path.join(out_dir, "metrics.prom")).read()
    tb_dir = os.path.join(out_dir, "tb")
    tb_files = [
        os.path.join(tb_dir, f) for f in os.listdir(tb_dir)
        if f.startswith("events.out.tfevents.")
    ]
    tb_events = read_scalar_events(tb_files[0]) if tb_files else []
    # ISSUE 19: the train-side HBM ledger — the four train components
    # recombine EXACTLY into the resident total (kv_cache stays None:
    # no serving subsystem in this run), the worst program transient
    # was captured at the dispatch funnel, predicted peak = resident +
    # transient, the build pre-flight was recorded (silent — the CPU
    # simulator reports no capacity to squeeze against), and the
    # resident gauge reached the exposition
    mem_preflights = stoke.memory.preflights if stoke.memory else {}
    memory_ok = (
        rec.get("mem/params_bytes") == 128
        and None not in (
            rec.get("mem/opt_state_bytes"),
            rec.get("mem/transport_bytes"),
            rec.get("mem/snapshot_bytes"),
        )
        and rec.get("mem/params_bytes")
        + rec.get("mem/opt_state_bytes")
        + rec.get("mem/transport_bytes")
        + rec.get("mem/snapshot_bytes")
        == rec.get("mem/resident_bytes")
        and rec.get("mem/kv_cache_bytes") is None
        and (rec.get("mem/temp_peak_bytes") or 0) > 0
        and rec.get("mem/predicted_peak_bytes")
        == rec.get("mem/resident_bytes") + rec.get("mem/temp_peak_bytes")
        and "build" in mem_preflights
        and mem_preflights["build"]["fired"] is False
        and "stoke_mem_resident_bytes" in prom
    )
    ok = (
        len(records) == 2
        and records[0]["step"] == 1
        and health_fields_ok
        and attribution_ok
        and fleet_ok
        and "stoke_jax_compiles_total" in prom
        and "stoke_health_anomalies_total" in prom
        and "stoke_goodput_productive_s_total" in prom
        and "stoke_attr_mfu" in prom
        and "stoke_fleet_windows_total" in prom
        and "stoke_sync_barriers_total" in prom
        and 'host="' in prom  # multi-host scrape-collision labels
        and any(t.startswith("telemetry/") for t, _, _ in tb_events)
        and bundle_ok
        and {"sentinels", "step_event"} <= ring_kinds
        and compile_cache_ok
        and resilience_ok
        and elastic_ok
        and zero_ok
        and serving_ok
        and tracing_ok
        and numerics_ok
        and memory_ok
        # ISSUE 12: the main run's record carries the per-group block
        # (one group: the single "w" param)
        and (rec.get("numerics/per_group") or {}).keys() == {"w"}
        # default-OFF discipline (ISSUE 9): training records never carry
        # serve fields — and (ISSUE 12) a run without a NumericsConfig
        # (the serve cycle's) never carries numerics fields; the
        # serve/cost_* block (ISSUE 18) rides the same contract, so a
        # non-serve record is cost-free by construction
        and not any(k.startswith("serve/") for k in rec)
        and not any(k.startswith("serve/cost_") for r in records for k in r)
        and not any(k.startswith("numerics/") for k in sv_rec)
        # ISSUE 19 default-OFF discipline: runs WITHOUT a MemoryConfig
        # (the sharded-transport and numerics legs) emit records with
        # zero mem/* fields — absent, never null
        and not any(k.startswith("mem/") for k in zero_rec)
        and not any(k.startswith("mem/") for k in nm_rec)
        and not any(k.startswith("mem/") for k in nm_clean_rec)
        # ISSUE 20: the live ops plane — six endpoints over real HTTP on
        # the training run, the /healthz 200→503 drain flip on the
        # injected-NaN halt, and the serve cycle's request table
        and opsplane_train_ok
        and opsplane_flip_ok
        and sv_result["opsplane_ok"]
    )
    print(json.dumps({
        "telemetry_smoke": "ok" if ok else "FAILED",
        "output_dir": out_dir,
        "jsonl_records": len(records),
        "prom_bytes": len(prom),
        "tb_scalars": len(tb_events),
        "bundle": bundle,
        "bundle_files": sorted(bundle_files),
        "ring_kinds": sorted(ring_kinds),
        "mfu": rec.get("mfu"),
        "bound": rec.get("bound"),
        "goodput_fraction": goodput.get("goodput_fraction"),
        "fleet_hosts": rec.get("fleet/hosts"),
        "fleet_windows": fleet.get("windows"),
        "fleet_skew_class": rec.get("fleet/skew_class"),
        "compile_cache_cold": cc_cold.compile_cache.stats(),
        "compile_cache_warm": cc_warm.compile_cache.stats(),
        "resilience_cycle": "ok" if resilience_ok else "FAILED",
        "resilience_resumed": rz_resumed.resilience_summary,
        "elastic_cycle": "ok" if elastic_ok else "FAILED",
        "elastic_resumed": el_sum.get("elastic_resumes"),
        "zero_sharded_step": "ok" if zero_ok else "FAILED",
        "zero_comm_compression": zero_rec.get("comm_compression"),
        "zero_param_gather_bytes": zero_rec.get("comm_bytes_param_gather"),
        "serve_cycle": "ok" if serving_ok else "FAILED",
        "serve_ttft_p50_s": sv_rec.get("serve/ttft_p50_s"),
        "serve_tpot_p50_s": sv_rec.get("serve/tpot_p50_s"),
        "serve_quant_compression": sv_rec.get("serve/quant_compression"),
        "serve_prefill_chunks": sv_rec.get("serve/prefill_chunks"),
        "serve_sampled_tokens": sv_rec.get("serve/sampled_tokens"),
        "serve_slo_attainment": sv_rec.get("serve/slo_attainment"),
        "serve_slo_coverage": sv_result["slo_attribution"].get(
            "span_coverage"
        ),
        "serve_cost_decode_bound": sv_rec.get("serve/cost_decode_bound"),
        "serve_cost_mfu": sv_rec.get("serve/cost_mfu"),
        "serve_verify_intensity_uplift": sv_result["cost_summary"].get(
            "verify_intensity_uplift"
        ),
        "numerics": "ok" if numerics_ok else "FAILED",
        "numerics_provenance": nm_rec.get("numerics/provenance_name"),
        "numerics_diff_aligned": diff_report.get("aligned_steps"),
        "memory": "ok" if memory_ok else "FAILED",
        "mem_resident_bytes": rec.get("mem/resident_bytes"),
        "mem_temp_peak_bytes": rec.get("mem/temp_peak_bytes"),
        "mem_preflight_fired": (
            mem_preflights.get("build") or {}
        ).get("fired"),
        "serve_memory": "ok" if sv_result["mem_ok"] else "FAILED",
        "serve_mem_resident_bytes": sv_rec.get("mem/resident_bytes"),
        "serve_mem_headroom_bytes": sv_rec.get("serve/mem_headroom_bytes"),
        "tracing": "ok" if tracing_ok else "FAILED",
        "trace_train_spans": len(train_trace),
        "trace_serve_spans": len(serve_trace),
        "trace_requests": sorted(spans_by_rid),
        "opsplane": (
            "ok"
            if opsplane_train_ok
            and opsplane_flip_ok
            and sv_result["opsplane_ok"]
            else "FAILED"
        ),
        "opsplane_healthz_flip": [hz_before, hz_after],
        "opsplane_halted": hz_verdict.get("halted"),
        "opsplane_profile_dir": ops_profile.get("trace_dir"),
        "opsplane_serve_queued": sv_result["opsplane_queued"],
    }))
    return 0 if ok else 1


def serve_only() -> int:
    """The ``make serve-smoke`` leg: just the traced serve cycle — one
    chunked-prefill + top-p request (plus two greedy ones and the
    ISSUE 17 speculative repetitive-prompt request) end-to-end, chunk
    spans asserted in the exported timeline and the speculative
    accept-rate / greedy-identity contract asserted on the counters."""
    out_dir = os.environ.get(
        "STOKE_TELEMETRY_SMOKE_DIR",
        tempfile.mkdtemp(prefix="stoke-serve-smoke-"),
    )
    res = run_serve_cycle(os.path.join(out_dir, "serve"))
    print(json.dumps({
        "serve_smoke": "ok" if res["ok"] else "FAILED",
        "output_dir": out_dir,
        "serve_prefill_chunks": res["record"].get("serve/prefill_chunks"),
        "serve_sampled_tokens": res["record"].get("serve/sampled_tokens"),
        "serve_quant_compression": res["record"].get(
            "serve/quant_compression"
        ),
        "chunk_spans": res["chunk_spans"],
        "long_request_tokens": res["long_tokens"],
        "serve_slo_attainment": res["record"].get("serve/slo_attainment"),
        "serve_slo_attribution": {
            k: res["slo_attribution"].get(k)
            for k in ("queue_wait_s", "prefill_blocked_s",
                      "decode_contention_s", "e2e_s", "span_coverage")
        },
        "spec_accept_rate": res["spec_accept_rate"],
        "spec_drafted": res["spec_drafted"],
        "spec_accepted": res["spec_accepted"],
        "spec_greedy_identity": res["greedy_identity"],
        "serve_memory": "ok" if res["mem_ok"] else "FAILED",
        "serve_mem_resident_bytes": res["record"].get("mem/resident_bytes"),
        "serve_mem_headroom_bytes": res["record"].get(
            "serve/mem_headroom_bytes"
        ),
        "serve_cost_decode_bound": res["record"].get(
            "serve/cost_decode_bound"
        ),
        "serve_cost_attainable_tpot_s": res["record"].get(
            "serve/cost_attainable_tpot_s"
        ),
        "serve_verify_intensity_uplift": res["cost_summary"].get(
            "verify_intensity_uplift"
        ),
        "trace_requests": sorted(res["spans_by_rid"]),
    }))
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(serve_only() if "--serve-only" in sys.argv[1:] else main())

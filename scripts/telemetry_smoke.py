"""Telemetry smoke: ONE CPU train step with the full pipeline enabled.

Proves the observability stack end-to-end in seconds (``make
telemetry-smoke``): a JSONL step record (schema-validated on read-back), a
Prometheus exposition file, and a TB event stream readable by the native
frame parser.  Prints the step record and a one-line verdict; exit 0 only
when all three sinks round-trip.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import numpy as np
    import optax

    from stoke_tpu import Stoke, StokeOptimizer, TelemetryConfig
    from stoke_tpu.telemetry import read_step_events
    from stoke_tpu.utils.tb_writer import read_scalar_events

    out_dir = os.environ.get(
        "STOKE_TELEMETRY_SMOKE_DIR",
        tempfile.mkdtemp(prefix="stoke-telemetry-smoke-"),
    )
    cfg = TelemetryConfig(
        output_dir=out_dir,
        log_every_n_steps=1,
        tensorboard=True,
        grad_norm=True,
    )
    stoke = Stoke(
        model=lambda p, x: x @ p["w"],
        optimizer=StokeOptimizer(
            optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.1}
        ),
        loss=lambda o, y: ((o - y) ** 2).mean(),
        params={"w": np.ones((8, 4), np.float32)},
        batch_size_per_device=16,
        configs=[cfg],
        verbose=False,
    )
    x = np.ones((16, 8), np.float32)
    y = np.zeros((16, 4), np.float32)
    stoke.train_step(x, (y,))
    stoke.close_telemetry()

    records = read_step_events(os.path.join(out_dir, "steps.jsonl"))
    print(json.dumps(records[-1], sort_keys=True))
    prom = open(os.path.join(out_dir, "metrics.prom")).read()
    tb_dir = os.path.join(out_dir, "tb")
    tb_files = [
        os.path.join(tb_dir, f) for f in os.listdir(tb_dir)
        if f.startswith("events.out.tfevents.")
    ]
    tb_events = read_scalar_events(tb_files[0]) if tb_files else []
    ok = (
        len(records) == 1
        and records[0]["step"] == 1
        and "stoke_jax_compiles_total" in prom
        and any(t.startswith("telemetry/") for t, _, _ in tb_events)
    )
    print(json.dumps({
        "telemetry_smoke": "ok" if ok else "FAILED",
        "output_dir": out_dir,
        "jsonl_records": len(records),
        "prom_bytes": len(prom),
        "tb_scalars": len(tb_events),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

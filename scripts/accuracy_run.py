"""Accuracy validation through the full training path (north-star proxy).

BASELINE.md's north star includes "top-1 accuracy parity" on CIFAR-10
ResNet-50 — but this environment has no real CIFAR-10 (zero egress; the
example falls back to synthetic data).  This script records REAL-data
accuracy through the exact same code path (Stoke facade, fused micro-step,
bf16 policy, ResNet) on the one real dataset available offline
(sklearn's handwritten digits, 1797 samples, 10 classes, upscaled 8x8→32x32)
plus a synthetic-CIFAR overfit check (loss → ~0 proves the optimizer/grad
path end-to-end).

Prints one JSON line per phase.  Run on TPU or CPU:
    python scripts/accuracy_run.py [--model resnet18|resnet50] [--epochs N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def ledger_note(backend: str, precision: str) -> str:
    """Derive the human-readable ledger note from the STRUCTURED
    backend/precision fields (VERDICT r5 #7 / ADVICE r4: the free text must
    agree with the structured provenance, because ``bench.record_backend``
    falls back on it for legacy records) — an eventual on-chip pass must
    never be labeled a "cpu rehearsal" and vice versa."""
    if backend == "cpu":
        return (
            f"cpu {precision} rehearsal (same facade/engine path; "
            f"on-chip re-run pending)"
        )
    return f"on-chip {precision} measurement ({backend} backend)"


def load_digits_32():
    from sklearn.datasets import load_digits

    d = load_digits()
    x = d.images.astype(np.float32) / 16.0  # [N, 8, 8] in [0, 1]
    x = np.kron(x, np.ones((1, 4, 4), np.float32))  # upscale to 32x32
    x = np.repeat(x[..., None], 3, axis=-1)  # fake RGB
    y = d.target.astype(np.int64)
    rng = np.random.default_rng(0)
    order = rng.permutation(len(x))
    x, y = x[order], y[order]
    n_test = 297
    return (x[n_test:], y[n_test:]), (x[:n_test], y[:n_test])


def build(model_name, num_classes, lr, steps_per_epoch, epochs,
          precision="auto"):
    import jax
    import optax

    from stoke_tpu import Stoke, StokeOptimizer
    from stoke_tpu.models import ResNet18, ResNet50
    from stoke_tpu.utils import init_module

    model = (ResNet18 if model_name == "resnet18" else ResNet50)(
        num_classes=num_classes, cifar_stem=True
    )
    variables = init_module(
        model, jax.random.PRNGKey(0), np.zeros((2, 32, 32, 3), np.float32),
        train=False,
    )
    sched = optax.cosine_decay_schedule(lr, steps_per_epoch * epochs)
    on_accel = jax.default_backend() not in ("cpu",)
    if precision == "auto":
        precision = "bf16" if on_accel else None
    return Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.sgd,
            optimizer_kwargs={"learning_rate": sched, "momentum": 0.9},
        ),
        loss=lambda lg, y: optax.softmax_cross_entropy_with_integer_labels(
            lg, y
        ).mean(),
        params=variables,
        batch_size_per_device=128,
        device="tpu" if on_accel else "cpu",
        precision=precision,
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        verbose=False,
    )


def evaluate(stoke, x, y, batch=128):
    import jax.numpy as jnp

    stoke.eval()
    correct = 0
    for i in range(0, len(x) - batch + 1, batch):
        out = stoke.model(x[i : i + batch])
        arr = np.asarray(out.value if hasattr(out, "value") else out)
        correct += int((arr.argmax(-1) == y[i : i + batch]).sum())
    n = (len(x) // batch) * batch
    stoke.train()
    return correct / max(n, 1)


def run_digits(model_name, epochs, augment=False, precision="auto"):
    (xt, yt), (xv, yv) = load_digits_32()
    batch = 128
    spe = len(xt) // batch
    stoke = build(model_name, 10, 0.02, spe, epochs, precision=precision)
    rng = np.random.default_rng(1)

    def shift_batch(xb):
        """Random ±3px 2D shifts (pad+crop), host-side: 1500 train samples
        overfit badly without it; digits must not be flipped/rotated."""
        pad = np.pad(xb, ((0, 0), (3, 3), (3, 3), (0, 0)), mode="constant")
        out = np.empty_like(xb)
        offs = rng.integers(0, 7, size=(len(xb), 2))
        for j, (dy, dx) in enumerate(offs):
            out[j] = pad[j, dy : dy + 32, dx : dx + 32]
        return out

    t0 = time.time()
    for ep in range(epochs):
        order = rng.permutation(len(xt))
        for i in range(spe):
            idx = order[i * batch : (i + 1) * batch]
            xb = xt[idx]
            if augment:
                xb = shift_batch(xb)
            stoke.train_step(xb, (yt[idx],))
    stoke.block_until_ready()
    wall = time.time() - t0
    acc = evaluate(stoke, xv, yv)
    print(json.dumps({
        "phase": "digits_real_data", "model": model_name, "epochs": epochs,
        "augment": augment,
        "precision": getattr(stoke.status["precision"], "name",
                             str(stoke.status["precision"])),
        "train_n": len(xt), "test_n": len(xv),
        "top1": round(acc, 4), "wall_s": round(wall, 1),
        "ema_loss": round(float(stoke.ema_loss), 4),
    }), flush=True)
    return acc


def run_precision_compare(model_name, epochs, augment):
    """bf16-vs-f32 numerics A/B at EQUAL settings on whatever backend is
    live (VERDICT r5 #3: retire the bf16 accuracy risk OFFLINE — the gate
    config passed at f32 on CPU but bf16 had never run on ANY backend).
    Runs the digits phase once per precision through the identical
    facade/engine path and ledgers BOTH results with honest
    backend/precision provenance.  Returns (acc_f32, acc_bf16)."""
    import time as _time

    import jax as _jax

    import bench as _bench

    backend = _jax.default_backend()
    results = {}
    for precision in ("full", "bf16"):
        t0 = _time.time()
        acc = run_digits(model_name, epochs, augment=augment,
                         precision=precision)
        results[precision] = acc
        try:
            _bench.persist_result(
                f"digits_{model_name}_top1_{precision}_{backend}_check",
                {
                    "value": round(float(acc), 4),
                    "unit": "top1_accuracy",
                    "vs_baseline": round(float(acc) / 0.95, 4),
                    "date": _time.strftime("%Y-%m-%d"),
                    "api": f"{model_name}/{epochs}ep"
                    + ("/augment" if augment else "")
                    + "/precision_compare",
                    "batch": 128,
                    "backend": backend,
                    "precision": precision,
                    "source": f"scripts/accuracy_run.py "
                    f"--compare-precisions on {backend}",
                    "note": ledger_note(backend, precision)
                    + " [equal-settings precision A/B]",
                    "wall_s": round(_time.time() - t0, 1),
                },
            )
        except Exception as e:
            print(json.dumps({"ledger_error": str(e)[:120]}), flush=True)
    delta = results["bf16"] - results["full"]
    print(json.dumps({
        "phase": "precision_compare", "model": model_name, "epochs": epochs,
        "backend": backend, "augment": augment,
        "top1_f32": round(float(results["full"]), 4),
        "top1_bf16": round(float(results["bf16"]), 4),
        "bf16_minus_f32": round(float(delta), 4),
        # parity verdict: bf16 within 2 points of f32 at equal settings
        # retires the "BN stats in bf16" numerics risk (flax BatchNorm
        # computes batch statistics in f32 regardless of the activation
        # dtype, and the framework keeps master params + batch_stats in f32)
        "bf16_parity": bool(delta >= -0.02),
    }), flush=True)
    return results["full"], results["bf16"]


def run_synthetic_overfit(model_name):
    """Memorize 512 random-label synthetic CIFAR images: loss -> ~0 and
    train-acc -> 1.0 proves the full grad/update path."""
    rng = np.random.default_rng(2)
    n = 512
    x = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=(n,)).astype(np.int64)
    batch = 128
    spe = n // batch
    epochs = 60
    stoke = build(model_name, 10, 0.05, spe, epochs)
    t0 = time.time()
    for ep in range(epochs):
        for i in range(spe):
            stoke.train_step(x[i * batch : (i + 1) * batch],
                             (y[i * batch : (i + 1) * batch],))
    stoke.block_until_ready()
    wall = time.time() - t0
    acc = evaluate(stoke, x, y)
    print(json.dumps({
        "phase": "synthetic_cifar_overfit", "model": model_name,
        "n": n, "epochs": epochs, "train_top1": round(acc, 4),
        "ema_loss": round(float(stoke.ema_loss), 4),
        "wall_s": round(wall, 1),
    }), flush=True)
    return acc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50",
                    choices=["resnet18", "resnet50"])
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--skip-overfit", action="store_true")
    ap.add_argument("--augment", action="store_true",
                    help="random-shift augmentation for the digits phase")
    ap.add_argument("--precision", default="auto",
                    choices=["auto", "full", "bf16"],
                    help="force the precision policy (default: bf16 on "
                    "accelerators, f32 on cpu)")
    ap.add_argument("--compare-precisions", action="store_true",
                    help="run the digits phase at f32 AND bf16 at equal "
                    "settings, ledger both (bf16 numerics A/B; VERDICT r5 #3)")
    ap.add_argument("--_worker", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()
    if not args._worker:
        from _supervise import supervise

        # budget covers the digits run, a possible precision-fallback
        # retry of the same length, and the overfit phase
        sys.exit(supervise(__file__, sys.argv[1:], watchdog_seconds=5400))
    t_main = time.time()
    if args.compare_precisions:
        acc_f32, acc_bf16 = run_precision_compare(
            args.model, args.epochs, args.augment
        )
        # the A/B is a numerics experiment, not the accuracy gate: exit 0
        # when bf16 holds parity (within 2 points) OR both arms pass the
        # gate outright
        ok = (acc_bf16 - acc_f32 >= -0.02) or (
            acc_f32 >= 0.95 and acc_bf16 >= 0.95
        )
        sys.exit(0 if ok else 1)
    # the supervising process (standalone supervise() or tpu_session's
    # umbrella) exports its absolute deadline; the optional f32 retry must
    # fit the REAL remaining budget, not a local guess
    deadline = float(os.environ.get("STOKE_SESSION_DEADLINE",
                                    t_main + 5400))
    acc = run_digits(args.model, args.epochs, augment=args.augment,
                     precision=args.precision)
    first_wall = time.time() - t_main
    import jax as _jx

    if args.precision != "auto":
        precision_used = args.precision
    else:
        precision_used = "bf16" if _jx.default_backend() != "cpu" else "full"
    if (acc < 0.95 and _jx.default_backend() != "cpu"
            and args.precision == "auto"
            and first_wall * 1.3 < deadline - time.time() - 600):
        # bf16 missed the gate on-chip: retry once in f32 before declaring
        # failure (the CPU rehearsal passed in f32; precision is our choice,
        # the gate metric is accuracy) — keep the better result.  Skipped
        # when the remaining watchdog budget cannot fit another run.
        print(json.dumps({"phase": "precision_fallback",
                          "bf16_top1": round(float(acc), 4)}), flush=True)
        acc_f32 = run_digits(args.model, args.epochs,
                             augment=args.augment, precision="full")
        if acc_f32 > acc:
            acc = acc_f32
            precision_used = "full"
    ok = acc >= 0.95
    if not args.skip_overfit:
        oacc = run_synthetic_overfit(args.model)
        ok = ok and oacc >= 0.99
    print(json.dumps({"accuracy_gate": "pass" if ok else "FAIL"}))
    # record GATE-PASSING measurements in the shared ledger (same place
    # bench.py persists throughput) so a later wedged-tunnel round can cite
    # them.  Keep-best semantics: a failing or worse run never clobbers a
    # better persisted record (bench.py guards its own persist the same
    # way; config lives in the api/note fields).
    try:
        import jax as _jax

        import bench as _bench

        metric = f"digits_{args.model}_top1"
        backend = _jax.default_backend()
        prev_rec = _bench._load_results().get(metric, {})
        prev = prev_rec.get("value", 0.0)
        # backend- and precision-aware keep-best (ADVICE r3 + review r4):
        # an accelerator measurement always outranks a CPU rehearsal, and
        # within on-chip results the bf16 policy (the headline config)
        # outranks an f32 fallback regardless of value — an f32 pass can
        # never mask a later genuine bf16 pass
        def _prec_rank(p):
            return 1 if p == "bf16" else 0

        rank = (0 if backend == "cpu" else 1,
                _prec_rank(precision_used), float(acc))
        prev_rank = (
            0 if _bench.record_backend(prev_rec) == "cpu" else 1,
            # legacy on-chip records predate the field and were bf16 runs
            _prec_rank(prev_rec.get("precision",
                                    "bf16" if _bench.record_backend(prev_rec)
                                    != "cpu" else "full")),
            float(prev),
        ) if prev_rec else (-1, -1, 0.0)
        if acc >= 0.95 and rank > prev_rank:
            _bench.persist_result(
                metric,
                {
                    "value": round(float(acc), 4),
                    "unit": "top1_accuracy",
                    "vs_baseline": round(float(acc) / 0.95, 4),  # 0.95 gate
                    "date": time.strftime("%Y-%m-%d"),
                    "api": f"{args.model}/{args.epochs}ep"
                    + ("/augment" if args.augment else ""),
                    "batch": 128,
                    "backend": backend,
                    "precision": precision_used,
                    "source": f"scripts/accuracy_run.py on {backend}",
                    # the note is DERIVED from the structured backend/
                    # precision fields (ledger_note) so an on-chip pass can
                    # never be mislabeled a cpu rehearsal (VERDICT r5 #7)
                    "note": ledger_note(backend, precision_used),
                },
            )
    except Exception as e:  # ledger write must never fail the gate run
        print(json.dumps({"ledger_error": str(e)[:120]}))
    sys.exit(0 if ok else 1)

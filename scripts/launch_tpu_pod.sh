#!/usr/bin/env bash
# One-command multi-host TPU pod bring-up for stoke_tpu.
#
# TPU translation of the reference's launcher story (docs/Launchers.md:
# torchrun / horovodrun / mpirun + docker/stoke-gpu-mpi.Dockerfile): on
# Cloud TPU there is no launcher zoo — `gcloud ... ssh --worker=all` starts
# ONE process per host and `jax.distributed.initialize()` (called inside
# Stoke.__init__) rendezvouses via the TPU metadata server.
#
# Usage:
#   scripts/launch_tpu_pod.sh create            # provision the pod slice
#   scripts/launch_tpu_pod.sh setup             # rsync repo + pip install on all workers
#   scripts/launch_tpu_pod.sh run CMD...        # run CMD on all workers simultaneously
#   scripts/launch_tpu_pod.sh train             # run the CIFAR-10 DP example
#   scripts/launch_tpu_pod.sh delete            # tear down
#
# Every gcloud invocation honors DRY_RUN=1 (print, don't execute), so the
# full bring-up is reviewable/dry-runnable without a GCP project:
#   DRY_RUN=1 scripts/launch_tpu_pod.sh create setup train
#
# Config via env (defaults target a v5e-16 slice = 4 hosts x 4 chips):
set -euo pipefail

TPU_NAME="${TPU_NAME:-stoke-tpu-pod}"
ZONE="${ZONE:-us-west4-a}"
ACCELERATOR_TYPE="${ACCELERATOR_TYPE:-v5litepod-16}"
RUNTIME_VERSION="${RUNTIME_VERSION:-v2-alpha-tpuv5-lite}"
PROJECT_ARGS=${PROJECT:+--project "$PROJECT"}
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
REMOTE_DIR="${REMOTE_DIR:-stoke_tpu}"

gcloud_tpu() {
  if [[ "${DRY_RUN:-0}" == "1" ]]; then
    echo "+ gcloud compute tpus tpu-vm $*"
  else
    # shellcheck disable=SC2086
    gcloud compute tpus tpu-vm "$@" $PROJECT_ARGS
  fi
}

cmd_create() {
  gcloud_tpu create "$TPU_NAME" \
    --zone "$ZONE" \
    --accelerator-type "$ACCELERATOR_TYPE" \
    --version "$RUNTIME_VERSION"
}

cmd_setup() {
  # rsync the repo to every worker, then install deps + the package.
  gcloud_tpu scp --recurse --worker=all --zone "$ZONE" \
    "$REPO_ROOT" "$TPU_NAME":"$REMOTE_DIR"
  gcloud_tpu ssh "$TPU_NAME" --worker=all --zone "$ZONE" --command \
    "pip install -q 'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html && pip install -q -e $REMOTE_DIR"
}

cmd_run() {
  # Simultaneous one-process-per-host launch; rendezvous is automatic.
  gcloud_tpu ssh "$TPU_NAME" --worker=all --zone "$ZONE" --command \
    "cd $REMOTE_DIR && $*"
}

cmd_train() {
  cmd_run "python examples/cifar10/train.py --config examples/cifar10/config/dp_bf16.yaml"
}

cmd_delete() {
  gcloud_tpu delete "$TPU_NAME" --zone "$ZONE" --quiet
}

if [[ $# -eq 0 ]]; then
  sed -n '2,20p' "$0"
  exit 1
fi
while [[ $# -gt 0 ]]; do
  case "$1" in
    create) cmd_create; shift ;;
    setup) cmd_setup; shift ;;
    train) cmd_train; shift ;;
    delete) cmd_delete; shift ;;
    run) shift; cmd_run "$@"; break ;;
    *) echo "unknown command: $1" >&2; exit 1 ;;
  esac
done

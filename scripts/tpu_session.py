"""Run the whole remaining TPU measurement queue in ONE process.

The tunnel lore (BENCH_NOTES.md) is that repeated backend bring-up/teardown
is what wedges the relay — so instead of four supervised processes, this
session runs each measurement script's worker main sequentially inside one
interpreter: one probe, one backend bring-up, one long watchdog.

    python scripts/tpu_session.py            # default queue
    python scripts/tpu_session.py --only flops_probe,bench
"""

from __future__ import annotations

import argparse
import json
import os
import runpy
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))
sys.path.insert(0, HERE)

from _supervise import supervise  # noqa: E402

#: name -> (path relative to repo root, worker argv)
QUEUE = {
    "flops_probe": ("scripts/flops_probe.py", []),
    "accuracy": ("scripts/accuracy_run.py",
                 ["--model", "resnet18", "--epochs", "120", "--augment",
                  "--skip-overfit"]),
    "longcontext": ("scripts/bench_longcontext.py", []),
    # composed-path rows (VERDICT r3 item 4): flash vs dense ring hop math;
    # on one chip the ring degenerates to a single hop — the dense arm OOMs
    # at 8k while flash runs, which is the comparison that matters there
    "op_ring": ("scripts/bench_longcontext.py",
                ["--op-ring", "--lengths", "1024,4096,8192", "--batch", "4"]),
    # realistic-vocab arm: at V=32k the [B, L, V] logits tensor is the
    # memory cliff; the flash+chunked_ce arm drops it (ops/chunked_ce.py)
    "chunked_ce": ("scripts/bench_longcontext.py",
                   ["--chunked-ce", "--vocab", "32768",
                    "--lengths", "4096,8192", "--batch", "2"]),
    # first hardware evidence for BASELINE config #5 (VERDICT r4 item 5):
    # bucketed sampler + grad-accum + clip, short measured run -> ledger
    "bert": ("scripts/onchip_probes.py", ["--only", "bert"]),
    # dynamic fp16 scaler overflow->backoff->regrowth observed on hardware
    # (VERDICT r4 item 6)
    "fp16_scaler": ("scripts/onchip_probes.py", ["--only", "fp16_scaler"]),
    # real-Mosaic kernel tests: flash fwd+bwd + ring+flash + zigzag +
    # chunked-CE on silicon (VERDICT r4 item 3)
    "flash_tests": ("scripts/onchip_probes.py", ["--only", "flash_tests"]),
    "bench": ("bench.py", []),
    # seg-50 arm: if the relay's per-dispatch round trip is a real cost,
    # a longer scan segment amortizes it 5x; bench persistence is
    # keep-best so whichever configuration is faster owns the headline
    "bench_seg50": ("bench.py", ["--seg", "50"]),
    # evidence capture for the 0.46x ResNet attack (VERDICT r3 item 2):
    # batch sweep + HLO op histogram + wall-clock breakdown
    "profile": ("scripts/profile_capture.py",
                ["--batches", "128,256,512,1024"]),
    # CPU-safe smoke of the runpy dispatch itself (not part of the default
    # queue): tiny preset, finishes in ~1 min off-chip
    "smoke": ("bench.py", ["--preset", "tiny"]),
}
# importance order: if the tunnel dies (or the watchdog fires) mid-session,
# everything already run has persisted — so the official bench headline
# comes FIRST, then the never-measured MFU numbers, the accuracy gate, the
# profiler evidence, and the long-context arms last (they have round-2
# hardware numbers already)
DEFAULT_QUEUE = ("bench", "flops_probe", "accuracy", "flash_tests",
                 "bert", "fp16_scaler", "profile", "bench_seg50",
                 "longcontext", "op_ring", "chunked_ce")

#: XLA-flag A/B arms (VERDICT r4 item 2 lever).  XLA_FLAGS are fixed at
#: backend init, so these CANNOT run inside the session worker's single
#: interpreter — the non-jax parent runs each as its own supervised
#: subprocess AFTER the main worker exits (never two tunnel clients at
#: once), and only when the main session succeeded (a mid-run wedge means
#: more dialing would deepen it).  bench.py records the flags in the
#: ledger; keep-best promotes a faster arm to the headline automatically.
FOLLOWUP_ARMS = (
    # NB: the "=" form is required — argparse rejects a separate value
    # token that itself starts with "--"
    ("bench.py",
     ["--xla-flags=--xla_tpu_enable_experimental_fusion_cost_model=true"]),
    # single-chip effect expected small (no collectives to hide), but the
    # scheduler also reorders HBM prefetch against compute — worth one arm
    ("bench.py",
     ["--xla-flags=--xla_tpu_enable_latency_hiding_scheduler=true"]),
    # gradient-transport A/B (ISSUE 2): int8 quantized gradient exchange
    # through the same bench path.  On one chip the mesh is 1-wide, so
    # this measures the quantize/dequantize + error-feedback overhead the
    # transport adds (the on-pod win is bytes-on-wire, covered by the
    # 8-device telemetry tests offline); a distinct configuration for the
    # ledger, never substituted for the headline
    ("bench.py", ["--comm-dtype=int8"]),
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--_worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--only", default=",".join(DEFAULT_QUEUE),
                    help="comma-separated subset of: " + ", ".join(QUEUE))
    args = ap.parse_args()
    if not args._worker:
        # Take the single-client tunnel lock ATOMICALLY (bench.py's
        # O_CREAT|O_EXCL + live-holder check) — the previous plain
        # ``open('w')`` silently clobbered a live measurement session's
        # lock, which is exactly the second-client dial the lock exists to
        # prevent (ADVICE medium).  A live holder means the tunnel is busy:
        # back off and exit nonzero so the caller/watcher retries later
        # instead of wedging the relay.
        import bench

        taken, holder = bench._try_acquire_tunnel_lock()
        if not taken and holder is not None:
            print(json.dumps({
                "session": "backoff",
                "error": f"tunnel held by live session (pid {holder}); "
                f"refusing to dial a second client into the single-client "
                f"relay",
            }), flush=True)
            sys.exit(75)  # EX_TEMPFAIL: retryable, not a failure of the queue
        # taken, or filesystem error (holder None): in the latter case
        # proceed unlocked — refusing to measure over a lock-file IO error
        # would starve the queue forever
        lock = bench._TUNNEL_LOCK if taken else None

        def _release_lock():
            nonlocal lock
            if lock:
                try:
                    os.remove(lock)
                except OSError:
                    pass
                lock = None

        try:
            # hang detection is idle-based (every queue item prints a JSON
            # line per phase; 1h of silence on a chip means a hang, not a
            # slow phase); the 6h absolute cap is a backstop only — a
            # healthy-but-slow 7-item session must never be rationed into
            # a mid-stream kill (itself a relay-wedge trigger)
            rc = supervise(__file__, sys.argv[1:],
                           watchdog_seconds=21600, idle_seconds=3600)
            # the lock stays held through the follow-up arms: supervise()
            # spawns them in --_worker mode (scripts/_supervise.py), which
            # skips bench's own lock-taking supervisor path — releasing
            # here would leave the relay unguarded and let the background
            # watcher dial a second client mid-arm
            if rc == 0 and args.only == ",".join(DEFAULT_QUEUE):
                root = os.path.dirname(HERE)
                for script, argv in FOLLOWUP_ARMS:
                    print(json.dumps({"session": "followup",
                                      "script": script, "argv": argv}),
                          flush=True)
                    arm_rc = supervise(os.path.join(root, script), argv,
                                       watchdog_seconds=2400,
                                       idle_seconds=1800)
                    print(json.dumps({"session": "followup", "script": script,
                                      "exit": arm_rc}), flush=True)
                    if arm_rc != 0:
                        # a killed arm may have wedged the relay — stop
                        # dialing, and exit nonzero so the watcher backs
                        # off instead of declaring the session complete
                        rc = arm_rc
                        break
            sys.exit(rc)
        finally:
            _release_lock()

    root = os.path.dirname(HERE)
    failures = 0
    for name in args.only.split(","):
        script, argv = QUEUE[name]
        path = os.path.join(root, script)
        print(json.dumps({"session": name, "script": script}), flush=True)
        sys.argv = [path, "--_worker", *argv]
        try:
            runpy.run_path(path, run_name="__main__")
        except SystemExit as e:
            if e.code not in (0, None):
                failures += 1
                print(json.dumps({"session": name, "exit": e.code}), flush=True)
        except Exception as e:
            failures += 1
            print(json.dumps({"session": name,
                              "error": f"{type(e).__name__}: {e}"[:200]}),
                  flush=True)
    print(json.dumps({"session": "done", "failures": failures}), flush=True)


if __name__ == "__main__":
    main()

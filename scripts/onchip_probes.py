"""On-chip probes queued behind the TPU tunnel (VERDICT r4 items 3/5/6).

Three independent phases, each printing JSON lines and (where a throughput
is measured) persisting to the BENCH_RESULTS.json ledger:

- ``--only bert``: BERT sequence classification (BASELINE.md capability
  config #5: bucketed sampler + grad accumulation + clipping) — one short
  measured run through the full facade path; records seq/s + tok/s and the
  loss descent.  First hardware evidence of any vintage for this config.
- ``--only fp16_scaler``: dynamic fp16 loss-scaler sanity on real hardware
  (engine.py functional scaler): a deliberately-huge init_scale forces
  overflow -> backoff, then a short growth_interval shows regrowth; the
  whole scale trajectory is logged step by step.
- ``--only flash_tests``: the real-Mosaic kernel test module
  (tests/test_flash_tpu.py — flash fwd+bwd, ring+flash composition,
  zigzag ring, chunked CE) under pytest on the live chip.

Run serialized on the TPU (supervised; tunnel is single-client):
    python scripts/onchip_probes.py [--only bert,fp16_scaler,flash_tests]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "scripts"))

from _supervise import supervise  # noqa: E402


def probe_bert(args) -> int:
    """Short measured BERT-seqcls run: bucketed sampler + grad-accum + clip
    (examples/bert_seqcls/train.py flow, measurement-hardened)."""
    import jax
    import optax

    from stoke_tpu import (
        BucketedDistributedSampler,
        ClipGradNormConfig,
        RaggedSequenceDataset,
        Stoke,
        StokeOptimizer,
    )
    from stoke_tpu.models import BertForSequenceClassification
    from stoke_tpu.utils import init_module

    on_accel = jax.default_backend() not in ("cpu",)
    size = args.size if on_accel else "tiny"
    r = np.random.default_rng(0)
    n = 2048 if on_accel else 512
    buckets = 4 if on_accel else 2  # sampler needs >= 100 samples/bucket
    lens = np.clip((r.pareto(2.5, size=n) + 1.0) * 8, 8, 128).astype(int)
    seqs = [r.integers(5, 1000, size=int(L)) for L in lens]
    labels = np.asarray([int((s < 50).sum() % 2) for s in seqs], np.int64)

    model = BertForSequenceClassification(
        vocab_size=1000, num_classes=2, size_name=size, max_len=256,
        dropout_rate=0.0,
    )
    variables = init_module(
        model, jax.random.PRNGKey(0), np.zeros((2, 16), np.int32),
        np.ones((2, 16), np.int32), train=False,
    )
    stoke = Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.adamw, optimizer_kwargs={"learning_rate": 3e-4}
        ),
        loss=lambda lg, y: optax.softmax_cross_entropy_with_integer_labels(
            lg, y
        ).mean(),
        params=variables,
        batch_size_per_device=args.batch,
        grad_accum=2,
        grad_clip=ClipGradNormConfig(max_norm=1.0),
        device="tpu" if on_accel else "cpu",
        precision="bf16" if on_accel else None,
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        verbose=False,
    )
    ragged = RaggedSequenceDataset(seqs, labels, pad_multiple=32)
    sampler = BucketedDistributedSampler(
        ragged, buckets=buckets, batch_size=stoke.batch_size,
        sorted_idx=ragged.sorted_idx(), num_replicas=1, rank=0,
    )
    loader = stoke.DataLoader(ragged, sampler=sampler)

    first_ema = None
    epochs = args.epochs
    n_seq = 0
    # token count stays ON DEVICE during timing (async .sum() dispatches,
    # no blocking fetch, no retained mask buffers); ONE fetch at the end
    n_tok_dev = None
    t0 = None
    for epoch in range(epochs):
        loader.set_epoch(epoch)
        for inputs, y in loader:
            out = stoke.model(inputs["input_ids"], inputs["attention_mask"])
            loss = stoke.loss(out, y)
            stoke.backward(loss)
            stoke.step()
            if first_ema is None:
                stoke.block_until_ready()
                first_ema = float(stoke.ema_loss)
                t0 = time.perf_counter()  # exclude compile from the rate
            else:
                s = inputs["attention_mask"].sum()
                n_tok_dev = s if n_tok_dev is None else n_tok_dev + s
                n_seq += y.shape[0]
    stoke.block_until_ready()
    dt = max(time.perf_counter() - t0, 1e-9)
    n_tok = 0 if n_tok_dev is None else int(jax.device_get(n_tok_dev))
    rec = {
        "probe": "bert_seqcls",
        "size": size,
        "batch": args.batch,
        "grad_accum": 2,
        "epochs": epochs,
        "seqs_per_sec": round(n_seq / dt, 1),
        "real_tok_per_sec": round(n_tok / dt, 1),
        "ema_loss_first": round(first_ema, 4),
        "ema_loss_last": round(float(stoke.ema_loss), 4),
        "loss_descended": bool(float(stoke.ema_loss) < first_ema),
        "on_accelerator": on_accel,
        "backend": jax.default_backend(),
    }
    print(json.dumps(rec), flush=True)
    if on_accel:
        import bench

        metric = f"bert_seqcls_{size}_bf16_train_seqs_per_sec"
        bench.persist_result(metric, {
            "value": rec["seqs_per_sec"],
            "unit": "seqs/sec/chip",
            "vs_baseline": 0.0,  # reference publishes no number for #5
            "date": time.strftime("%Y-%m-%d"),
            "api": "4call+bucketed_sampler",
            "batch": args.batch,
            "backend": jax.default_backend(),
            "source": "scripts/onchip_probes.py --only bert",
            "note": f"on-chip bf16 measurement; real tok/s "
            f"{rec['real_tok_per_sec']}, ema loss "
            f"{rec['ema_loss_first']} -> {rec['ema_loss_last']}",
        }, keep_best=True)
    # the descent gate is the on-chip deliverable; the CPU flow smoke is
    # informational (tiny model + tiny corpus may not descend in 2 epochs)
    return 0 if (rec["loss_descended"] or not on_accel) else 1


def probe_fp16_scaler(args) -> int:
    """Overflow -> backoff -> regrowth of the dynamic fp16 scaler, observed
    on hardware step by step (engine.py:265-306; CPU-tested in
    tests/test_per_loss_scaler.py)."""
    import jax
    import optax

    from stoke_tpu import PrecisionConfig, Stoke, StokeOptimizer
    from stoke_tpu.models import BasicNN
    from stoke_tpu.utils import init_module

    on_accel = jax.default_backend() not in ("cpu",)
    model = BasicNN()
    x0 = np.zeros((2, 32, 32, 3), np.float32)
    variables = init_module(model, jax.random.PRNGKey(0), x0, train=False)
    stoke = Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.05}
        ),
        loss=lambda lg, y: optax.softmax_cross_entropy_with_integer_labels(
            lg, y
        ).mean(),
        params=variables,
        batch_size_per_device=64,
        device="tpu" if on_accel else "cpu",
        precision="fp16",
        # huge init_scale: scaled fp16 grads overflow immediately, forcing
        # visible backoff; short growth_interval shows regrowth in-probe
        configs=[PrecisionConfig(init_scale=2.0**24, growth_interval=5)],
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        verbose=False,
    )
    r = np.random.default_rng(0)
    x = jax.device_put(r.normal(size=(64, 32, 32, 3)).astype(np.float32))
    y = jax.device_put(r.integers(0, 10, size=(64,)))
    trajectory = []
    for i in range(args.steps):
        stoke.train_step(x, (y,))
        scale = stoke.loss_scale  # facade already returns a host float
        trajectory.append(scale)
        print(json.dumps({
            "probe": "fp16_scaler", "step": i, "loss_scale": scale,
            "optimizer_steps": int(stoke.optimizer_steps),
        }), flush=True)
    backoffs = sum(b < a for a, b in zip(trajectory, trajectory[1:]))
    growths = sum(b > a for a, b in zip(trajectory, trajectory[1:]))
    summary = {
        "probe": "fp16_scaler",
        "backend": jax.default_backend(),
        "on_accelerator": on_accel,
        "init_scale": 2.0**24,
        "final_scale": trajectory[-1],
        "backoffs": backoffs,
        "growths": growths,
        # the full cycle on this backend: overflow shrank the scale and
        # finite steps regrew it.  (Skip-on-overflow of the masked apply is
        # numerics-tested in tests/test_per_loss_scaler.py; the host-side
        # optimizer_steps counter counts dispatches, not applies, so it
        # cannot observe skips.)
        "ok": bool(backoffs >= 1 and growths >= 1),
    }
    print(json.dumps(summary), flush=True)
    return 0 if summary["ok"] else 1


def run_flash_tests() -> int:
    """tests/test_flash_tpu.py (real Mosaic kernels) on the live chip."""
    import pytest

    os.environ["STOKE_TEST_TPU"] = "1"
    rc = pytest.main([
        "-q", "-p", "no:cacheprovider",
        os.path.join(_REPO, "tests", "test_flash_tpu.py"),
    ])
    print(json.dumps({"probe": "flash_tests", "pytest_rc": int(rc)}),
          flush=True)
    return int(rc)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--_worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--only", default="bert,fp16_scaler,flash_tests")
    ap.add_argument("--size", default="base", help="BERT size on-accel")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--steps", type=int, default=25,
                    help="fp16 scaler probe steps")
    args = ap.parse_args()
    if not args._worker:
        # Standalone supervised runs must hold the single-client tunnel
        # lock for their whole duration — otherwise the background watcher
        # (or a parallel bench) dials a second client into the relay
        # mid-probe, the documented wedge trigger (ADVICE low).  Inside
        # tpu_session.py the session parent already holds the lock and the
        # probes run as plain workers, so this path is standalone-only.
        import bench

        taken, holder = bench._try_acquire_tunnel_lock()
        if not taken and holder is not None:
            print(json.dumps({
                "probe": "backoff",
                "error": f"tunnel held by live session (pid {holder}); "
                f"not dialing a second client into the single-client relay",
            }), flush=True)
            sys.exit(75)  # EX_TEMPFAIL: retry later
        try:
            sys.exit(supervise(__file__, sys.argv[1:], watchdog_seconds=3000,
                               idle_seconds=1200))
        finally:
            if taken:
                try:
                    os.remove(bench._TUNNEL_LOCK)
                except OSError:
                    pass
    failures = 0
    for name in args.only.split(","):
        try:
            if name == "bert":
                failures += probe_bert(args) != 0
            elif name == "fp16_scaler":
                failures += probe_fp16_scaler(args) != 0
            elif name == "flash_tests":
                failures += run_flash_tests() != 0
            else:
                raise ValueError(f"unknown probe {name!r}")
        except Exception as e:
            failures += 1
            print(json.dumps({
                "probe": name, "error": f"{type(e).__name__}: {e}"[:200],
            }), flush=True)
    print(json.dumps({"probe": "done", "failures": failures}), flush=True)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()

"""Telemetry-driven autotune sweep (ISSUE 6): search the exposed knobs,
score on MFU + goodput, persist the winner in the BENCH ledger.

The parent process NEVER imports jax (``XLA_FLAGS`` are fixed at backend
init, so every trial must be its own process — the discipline
``scripts/profile_capture.py`` established).  Each trial is a subprocess
whose environment carries the trial's flags; the worker builds a Stoke
run with the telemetry + attribution vertical enabled, measures
throughput via delta timing, and reports ``value`` / ``mfu`` /
``goodput_fraction`` / ``bound`` as one JSON line.  The search loop
(``stoke_tpu.autotune.greedy_search``) prunes the knob space with the
baseline's bound classification — a memory-bound workload does not burn
trial budget on compute flags.

Winners land in ``BENCH_RESULTS.json`` under ``autotune/<metric>`` with
full provenance (config key, flags, measured MFU, trial count); replay
with ``python bench.py --tuned``.

Usage:
    python scripts/autotune.py --smoke          # CPU flow validation
    python scripts/autotune.py --trials 12      # real sweep (takes the
                                                # tunnel lock; TPU flags)
    python scripts/autotune.py --workload flash --seq-len 4096
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)


def _load_autotune_module():
    """Load ``stoke_tpu/autotune.py`` by FILE, not through the package:
    ``import stoke_tpu.autotune`` executes the package ``__init__``,
    which imports the facade and therefore jax — exactly the import the
    jax-free parent must never pay (beyond cost, parent-side jax would
    freeze a backend whose XLA_FLAGS no trial chose)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_stoke_autotune_standalone",
        os.path.join(REPO, "stoke_tpu", "autotune.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    # dataclass field-type resolution looks the class's module up in
    # sys.modules — register before exec
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


_autotune = _load_autotune_module()
TPU_XLA_FLAG_CANDIDATES = _autotune.TPU_XLA_FLAG_CANDIDATES
SearchOutcome = _autotune.SearchOutcome
TrialResult = _autotune.TrialResult
TrialSpec = _autotune.TrialSpec
greedy_search = _autotune.greedy_search
persist_winner = _autotune.persist_winner

LEDGER_DEFAULT = os.path.join(REPO, "BENCH_RESULTS.json")
RESNET_METRIC = "cifar10_resnet50_bf16_train_throughput"
SMOKE_METRIC = "cifar10_basicnn_train_throughput"
FLASH_METRIC = "flash_attention_fwdbwd_tokens_per_s"
SERVE_DECODE_METRIC = "serve_paged_decode_tokens_per_s"


def _parse_int_list(text: str) -> list:
    return [int(v) for v in text.split(",") if v.strip()]


# --------------------------------------------------------------------------- #
# trial worker (its own process: XLA_FLAGS are already in its environment)
# --------------------------------------------------------------------------- #


def _run_trial(payload: dict) -> dict:
    """Measure ONE trial.  Runs inside the subprocess the driver spawned;
    prints nothing itself — returns the result record the caller emits."""
    import numpy as np

    import jax

    spec = TrialSpec.from_dict(payload["spec"])
    steps = int(payload["steps"])
    warmup = int(payload["warmup"])
    on_accel = jax.default_backend() not in ("cpu",)
    out = {
        "trial": True,
        "config_key": spec.config_key(),
        "on_accelerator": on_accel,
        "ok": True,
    }

    if payload["workload"] == "flash":
        return {**out, **_measure_flash(spec, payload, steps, warmup)}
    if payload["workload"] == "serve_decode":
        return {**out, **_measure_serve_decode(spec, payload, steps, warmup)}

    import optax

    from stoke_tpu import (
        AttributionConfig,
        CommConfig,
        Stoke,
        StokeOptimizer,
        TelemetryConfig,
    )
    from stoke_tpu.models import BasicNN, ResNet50
    from stoke_tpu.telemetry import read_step_events
    from stoke_tpu.utils import init_module

    smoke = payload["workload"] == "smoke"
    # dp is a SWEEP-level decision, not a per-trial one: when any trial
    # sweeps comm_dtype, every trial (baseline included) runs under
    # distributed="dp" so the score compares wire formats, never the
    # dp/no-dp switch itself.  The sharding tier (ISSUE 8) follows the
    # same rule: every trial of a --comm-shard-tier sweep runs under the
    # tier, so a comm_dtype winner is measured against a same-tier
    # baseline (the sddp/fsdp trials take the sharded weight-update path
    # automatically — CommConfig.shard_updates auto-resolution)
    use_dp = bool(payload.get("dp") or spec.comm_dtype)
    shard_tier = payload.get("comm_shard_tier")
    batch = spec.batch or (8 if smoke else 256)
    seg = spec.steps_per_dispatch or (2 if smoke else 10)
    model = BasicNN() if smoke else ResNet50(num_classes=10, cifar_stem=True)
    variables = init_module(
        model, jax.random.PRNGKey(0),
        np.zeros((2, 32, 32, 3), np.float32), train=False,
    )
    obs_dir = tempfile.mkdtemp(prefix="stoke-autotune-obs-")
    configs = [
        TelemetryConfig(
            output_dir=obs_dir, log_every_n_steps=1,
            prometheus=False, tensorboard=False, sample_device_time=False,
        ),
        AttributionConfig(peak_tflops=float(payload["peak_tflops"])),
    ]
    if spec.comm_dtype:
        # oss tier: shard_updates' auto default resolves replicated, so
        # the tier sweep must opt in explicitly — otherwise every trial
        # measures the replicated exchange while the winner persists
        # under the `_shard_oss` metric (sddp/fsdp auto-engage)
        configs.append(CommConfig(
            dtype=spec.comm_dtype,
            shard_updates=True if shard_tier == "oss" else None,
        ))
    stoke = Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.sgd,
            optimizer_kwargs={"learning_rate": 0.05, "momentum": 0.9},
        ),
        loss=lambda lo, la: optax.softmax_cross_entropy_with_integer_labels(
            lo, la
        ).mean(),
        params=variables,
        batch_size_per_device=batch,
        device="tpu" if on_accel else "cpu",
        distributed="dp" if use_dp else None,
        oss=shard_tier in ("oss", "sddp"),
        sddp=shard_tier == "sddp",
        fsdp=shard_tier == "fsdp",
        precision=None if smoke else "bf16",
        configs=configs,
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        verbose=False,
    )
    r = np.random.default_rng(0)
    xs = jax.device_put(
        r.normal(size=(seg, batch, 32, 32, 3)).astype(np.float32)
    )
    ys = jax.device_put(r.integers(0, 10, size=(seg, batch)))

    def timed(n):
        t0 = time.perf_counter()
        last = None
        for _ in range(n):
            last = stoke.train_steps(xs, (ys,))
        np.asarray(jax.tree_util.tree_leaves(last)[0])  # force a fetch
        return time.perf_counter() - t0

    for _ in range(warmup):
        stoke.train_steps(xs, (ys,))
    timed(1)
    t1 = timed(steps)
    t2 = timed(2 * steps)
    dt = max(t2 - t1, 1e-9)
    value = batch * seg * steps / dt
    goodput = stoke.goodput or {}
    stoke.close_telemetry()
    bound = None
    try:
        records = read_step_events(os.path.join(obs_dir, "steps.jsonl"))
        for rec in reversed(records):
            if rec.get("bound") is not None:
                bound = rec["bound"]
                break
    except Exception:
        pass
    return {
        **out,
        "value": round(value, 1),
        "unit": "imgs/sec/chip",
        "mfu": goodput.get("mfu"),
        "goodput_fraction": goodput.get("goodput_fraction"),
        "bound": bound,
        "wall_s": round(dt, 4),
        "batch": batch,
        "steps_per_dispatch": seg,
    }


def _measure_flash(spec: TrialSpec, payload: dict, steps: int,
                   warmup: int) -> dict:
    """Flash-attention block-size trial: fwd+bwd latency of the Pallas
    kernel at the spec's blocking (interpret mode on CPU — tiny sizes
    only; real sweeps run on the chip)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from stoke_tpu.ops.flash_attention import flash_attention

    on_cpu = jax.default_backend() == "cpu"
    L = int(payload["seq_len"])
    B, H, D = (1, 2, 64) if on_cpu else (4, 8, 64)
    r = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(r.normal(size=(B, H, L, D)).astype(np.float32))
        for _ in range(3)
    )

    def loss(q, k, v):
        o = flash_attention(
            q, k, v, causal=True,
            block_q=spec.flash_block_q, block_k=spec.flash_block_k,
            interpret=on_cpu,
        )
        return (o * o).sum()

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    for _ in range(max(warmup, 1)):
        jax.block_until_ready(g(q, k, v))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = g(q, k, v)
    jax.block_until_ready(out)
    dt = max(time.perf_counter() - t0, 1e-9)
    return {
        "value": round(B * L * steps / dt, 1),
        "unit": "tokens/sec",
        "mfu": None,
        "goodput_fraction": None,
        "bound": None,
        "wall_s": round(dt, 4),
    }


def _measure_serve_decode(spec: TrialSpec, payload: dict, steps: int,
                          warmup: int) -> dict:
    """Paged-decode kernel trial (ISSUE 13): steady-state latency of
    ``paged_decode_attention_pallas`` at the spec's block knobs over a
    synthetic full block pool — the decode-attention dispatch isolated
    from the rest of the serve loop, so the sweep scores exactly what the
    knobs move (the HBM→VMEM streaming schedule).  With ``spec_k`` in the
    payload (``--spec-k``, ISSUE 17) the trial measures the k-token
    verify kernel instead — ``paged_verify_attention_pallas`` at the
    spec's ``verify_pages_per_block`` / ``verify_block_h`` over S=k+1
    query rows per sequence, scored as candidate tokens per second (each
    dispatch scores S positions per slot).  CPU trials run the
    interpreter on tiny shapes (flow validation only); real sweeps run on
    the chip under the tunnel lock like every other workload."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from stoke_tpu.ops.flash_attention import (
        paged_decode_attention_pallas,
        paged_verify_attention_pallas,
    )

    on_cpu = jax.default_backend() == "cpu"
    spec_k = payload.get("spec_k")
    # geometry: a full decode batch over a GPT-small-class cache on chip;
    # a toy pool under the interpreter
    B, H, D, BS = (2, 2, 16, 8) if on_cpu else (8, 8, 64, 16)
    L = int(payload["seq_len"]) if not on_cpu else 64
    MB = -(-L // BS)
    NB = B * MB + 1
    r = np.random.default_rng(0)
    k_pages = jnp.asarray(r.normal(size=(NB, BS, H, D)).astype(np.float32))
    v_pages = jnp.asarray(r.normal(size=(NB, BS, H, D)).astype(np.float32))
    tables = jnp.asarray(
        np.arange(1, B * MB + 1, dtype=np.int32).reshape(B, MB)
    )
    # ragged contexts keep the masked tail honest (the serve batch is
    # never uniformly full)
    ctx = np.linspace(L // 2, L, B, dtype=np.int32)

    if spec_k is not None:
        S = int(spec_k) + 1
        # verify-shaped batch: S consecutive query positions per slot
        # ending at the slot's context frontier (the draft window)
        positions = jnp.asarray(
            np.stack([np.arange(c - S, c, dtype=np.int32) for c in ctx])
        )
        q = jnp.asarray(r.normal(size=(B, H, S, D)).astype(np.float32))
        fn = jax.jit(
            lambda q_, k_, v_, t_, p_: paged_verify_attention_pallas(
                q_, k_, v_, t_, p_,
                pages_per_block=spec.verify_pages_per_block,
                block_h=spec.verify_block_h,
                interpret=on_cpu,
            )
        )
        args5 = (q, k_pages, v_pages, tables, positions)
        per_dispatch = B * S  # candidate positions scored per dispatch
    else:
        q = jnp.asarray(r.normal(size=(B, H, 1, D)).astype(np.float32))
        fn = jax.jit(
            lambda q_, k_, v_, t_, c_: paged_decode_attention_pallas(
                q_, k_, v_, t_, c_,
                pages_per_block=spec.decode_pages_per_block,
                block_h=spec.decode_block_h,
                interpret=on_cpu,
            )
        )
        args5 = (q, k_pages, v_pages, tables, jnp.asarray(ctx))
        per_dispatch = B  # one decode dispatch = one fresh token per slot

    for _ in range(max(warmup, 1)):
        jax.block_until_ready(fn(*args5))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args5)
    jax.block_until_ready(out)
    dt = max(time.perf_counter() - t0, 1e-9)
    return {
        "value": round(per_dispatch * steps / dt, 1),
        "unit": "tokens/sec",
        "mfu": None,
        "goodput_fraction": None,
        "bound": None,
        "wall_s": round(dt, 4),
    }


# --------------------------------------------------------------------------- #
# driver (jax-free)
# --------------------------------------------------------------------------- #


def _subprocess_measure(payload_base: dict, timeout: int, verbose: bool,
                        require_accel: bool = False):
    """Build the measure() callable the search loop drives: one fresh
    subprocess per trial so the trial's XLA_FLAGS land before jax import
    (flags are fixed at backend init — the bench.py:500 bug this PR
    fixes was exactly an in-process mutation after import)."""

    def measure(spec: TrialSpec) -> TrialResult:
        payload = {**payload_base, "spec": spec.to_dict()}
        env = dict(os.environ)
        if spec.xla_flags:
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "") + " " + spec.xla_flags
            ).strip()
        try:
            proc = subprocess.run(
                [
                    sys.executable, os.path.abspath(__file__),
                    "--_trial", json.dumps(payload),
                ],
                capture_output=True, text=True, timeout=timeout, env=env,
            )
        except subprocess.TimeoutExpired:
            return TrialResult(
                spec, ok=False, error=f"trial timed out after {timeout}s"
            )
        line = next(
            (
                ln for ln in reversed(proc.stdout.strip().splitlines())
                if ln.startswith("{")
            ),
            None,
        )
        if proc.returncode != 0 or line is None:
            err = (proc.stderr or "no output").strip().splitlines()
            return TrialResult(
                spec, ok=False,
                error=(err[-1][:200] if err else "trial produced no JSON"),
            )
        rec = json.loads(line)
        if verbose:
            print(json.dumps(rec), flush=True)
        if not rec.get("ok", False):
            return TrialResult(
                spec, ok=False, error=rec.get("error", "trial failed")
            )
        if require_accel and rec.get("on_accelerator") is False:
            # tunnel down / backend fell back to CPU: the measurement is
            # real but its knobs are meaningless for the chip — a failed
            # trial, never a ledgered on-chip winner (the masquerade
            # bench.py's on_accelerator checks refuse)
            return TrialResult(
                spec, ok=False,
                error="trial ran on the CPU backend; refusing to score a "
                "CPU fallback in an on-chip sweep",
            )
        return TrialResult(
            spec,
            value=float(rec.get("value", 0.0)),
            unit=rec.get("unit", "imgs/sec/chip"),
            mfu=rec.get("mfu"),
            goodput_fraction=rec.get("goodput_fraction"),
            bound=rec.get("bound"),
            wall_s=rec.get("wall_s"),
        )

    return measure


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--_trial", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--smoke", action="store_true",
                    help="CPU flow validation: BasicNN, tiny knob space, "
                    ">= 4 trials, winner persisted under the smoke metric")
    ap.add_argument("--workload", choices=["resnet", "flash", "serve_decode"],
                    default="resnet")
    ap.add_argument("--trials", type=int, default=12,
                    help="total trial budget (baseline included)")
    ap.add_argument("--steps", type=int, default=None,
                    help="timed train_steps dispatches per trial")
    ap.add_argument("--warmup", type=int, default=None)
    ap.add_argument("--batches", default=None,
                    help="comma-separated batch candidates")
    ap.add_argument("--segs", default=None,
                    help="comma-separated steps_per_dispatch candidates")
    ap.add_argument("--xla-flag-candidates", default=None,
                    help="';'-separated XLA_FLAGS fragment candidates "
                    "(default: the curated TPU set; empty string = none)")
    ap.add_argument("--comm-dtypes", default=None,
                    help="comma-separated wire dtypes to sweep (e.g. "
                    "bf16,int8); default: not swept")
    ap.add_argument("--comm-shard-tier", default=None,
                    choices=["none", "oss", "sddp", "fsdp"],
                    help="run EVERY trial of the sweep under this sharding "
                    "tier (ISSUE 8 weight-update sharding) — a sweep-level "
                    "decision like dp, so a comm_dtype sweep measures the "
                    "sharded wire formats against a same-tier baseline "
                    "instead of confounding them with the tier switch.  "
                    "The winner persists under a tier-suffixed metric")
    ap.add_argument("--flash-blocks", default=None,
                    help="flash block-size candidates (workload=flash; "
                    "default 128,256,512, smoke 64,128)")
    ap.add_argument("--decode-pages", default=None,
                    help="decode_pages_per_block candidates "
                    "(workload=serve_decode; default 1,2,4,8, smoke 1,2)")
    ap.add_argument("--decode-block-hs", default=None,
                    help="decode_block_h candidates "
                    "(workload=serve_decode; default 1,2, smoke 1,2)")
    ap.add_argument("--spec-k", type=int, default=None,
                    help="speculative draft length k (workload="
                    "serve_decode; ISSUE 17): sweep the k-token VERIFY "
                    "kernel's verify_pages_per_block / verify_block_h "
                    "instead of the single-token decode knobs — S=k+1 "
                    "query rows per sequence, scored as candidate "
                    "positions per second.  The winner persists under a "
                    "_spec_k<k>-suffixed metric (a verify-kernel winner "
                    "is never the decode-kernel winner)")
    ap.add_argument("--seq-len", type=int, default=None,
                    help="sequence length for workload=flash / cached "
                    "context length for workload=serve_decode")
    ap.add_argument("--peak-tflops", type=float, default=None,
                    help="MFU denominator for trial attribution "
                    "(default: 197 = v5e bf16 dense; smoke: 1e-3)")
    ap.add_argument("--ledger", default=LEDGER_DEFAULT,
                    help="BENCH ledger path the winner persists into")
    ap.add_argument("--trial-timeout", type=int, default=900)
    ap.add_argument("--no-persist", action="store_true",
                    help="run the sweep but skip the ledger write")
    args = ap.parse_args()
    if args.comm_shard_tier and not args.comm_dtypes:
        ap.error("--comm-shard-tier requires --comm-dtypes (a tier sweep "
                 "without the wire-format knob never engages the sharded "
                 "transport, yet would persist its winner under the "
                 "tier-suffixed metric bench.py --tuned replays)")

    if args._trial is not None:
        # worker mode: measure one spec, emit one JSON line, exit
        payload = json.loads(args._trial)
        try:
            rec = _run_trial(payload)
        except Exception as e:  # the driver scores failures, not tracebacks
            rec = {
                "trial": True, "ok": False,
                "config_key": TrialSpec.from_dict(
                    payload.get("spec", {})
                ).config_key(),
                "error": f"{type(e).__name__}: {e}"[:300],
            }
        print(json.dumps(rec), flush=True)
        return 0 if rec.get("ok") else 1

    smoke = args.smoke
    flash = args.workload == "flash"
    serve_decode = args.workload == "serve_decode"
    if flash:
        # smoke runs persist under their own metric: a CPU interpret-mode
        # winner must never masquerade as a real on-chip flash record
        metric = FLASH_METRIC + ("_smoke" if smoke else "")
        blocks = _parse_int_list(
            args.flash_blocks or ("64,128" if smoke else "128,256,512")
        )
        space = {"flash_block_q": blocks, "flash_block_k": blocks}
        base = TrialSpec(flash_block_q=blocks[0], flash_block_k=blocks[0])
    elif serve_decode:
        # ISSUE 13 satellite: the serve side's ledgered on-chip number —
        # sweep the streaming decode kernel's block knobs, same tunnel-
        # lock discipline and CPU-fallback refusal as the other real
        # sweeps; smoke winners carry the _smoke suffix so interpreter
        # tokens/s never masquerade as a chip capture
        metric = SERVE_DECODE_METRIC + ("_smoke" if smoke else "")
        pages = _parse_int_list(
            args.decode_pages or ("1,2" if smoke else "1,2,4,8")
        )
        heads = _parse_int_list(args.decode_block_hs or "1,2")
        if args.spec_k is not None:
            # ISSUE 17: the speculative variant sweeps the verify
            # kernel's knobs under its own metric suffix
            metric = (
                SERVE_DECODE_METRIC + f"_spec_k{args.spec_k}"
                + ("_smoke" if smoke else "")
            )
            space = {
                "verify_pages_per_block": pages,
                "verify_block_h": heads,
            }
            base = TrialSpec(
                verify_pages_per_block=pages[0], verify_block_h=heads[0]
            )
        else:
            space = {
                "decode_pages_per_block": pages, "decode_block_h": heads
            }
            base = TrialSpec(
                decode_pages_per_block=pages[0], decode_block_h=heads[0]
            )
    else:
        # baselines carry the workload defaults EXPLICITLY (batch 8/256,
        # seg 2/10 — what the worker would fall back to anyway) so the
        # config-key dedup skips candidates that merely restate them: a
        # real on-chip trial is minutes of tunnel time, and re-measuring
        # the baseline under a different key wastes budget
        metric = SMOKE_METRIC if smoke else RESNET_METRIC
        if smoke:
            space = {
                "batch": args.batches and _parse_int_list(args.batches)
                or [16, 32],
                "steps_per_dispatch": args.segs and _parse_int_list(args.segs)
                or [4, 8],
            }
            base = TrialSpec(batch=8, steps_per_dispatch=2)
        else:
            space = {
                "xla_flags": (
                    args.xla_flag_candidates.split(";")
                    if args.xla_flag_candidates is not None
                    else list(TPU_XLA_FLAG_CANDIDATES)
                ),
                "batch": _parse_int_list(args.batches or "128,256,512"),
                "steps_per_dispatch": _parse_int_list(args.segs or "10,25,50"),
            }
            if args.comm_dtypes:
                space["comm_dtype"] = [
                    d for d in args.comm_dtypes.split(",") if d.strip()
                ]
            base = TrialSpec(batch=256, steps_per_dispatch=10)

    payload_base = {
        "workload": (
            "smoke" if (smoke and not flash and not serve_decode)
            else args.workload
        ),
        "steps": args.steps or (2 if smoke else 10),
        "warmup": args.warmup if args.warmup is not None else (1 if smoke else 2),
        "peak_tflops": (
            args.peak_tflops
            if args.peak_tflops is not None
            else (1e-3 if smoke else 197.0)
        ),
        "seq_len": args.seq_len
        or (128 if smoke else (2048 if serve_decode else 4096)),
        # speculative verify-kernel variant (ISSUE 17): k drafts -> the
        # trial measures S=k+1 query rows through the verify kernel
        "spec_k": args.spec_k if serve_decode else None,
        # dp for EVERY trial of a comm sweep (baseline included), so the
        # comm_dtype knob is measured against a dp baseline instead of
        # confounding the wire format with the dp/no-dp switch
        "dp": "comm_dtype" in space or bool(args.comm_shard_tier),
        # sharding tier for EVERY trial (ISSUE 8): same sweep-level rule —
        # the comm_dtype knob under a sharded tier is measured against a
        # same-tier baseline
        "comm_shard_tier": args.comm_shard_tier,
    }
    if args.comm_shard_tier:
        # the tier is part of the measured configuration: its winner must
        # never shadow (nor be replayed as) the unsharded metric's
        metric += f"_shard_{args.comm_shard_tier}"

    # tunnel discipline: a real (non-smoke) sweep dials the single-client
    # TPU relay once per trial — take the shared lock for the whole sweep
    # so the watcher/bench never double-dial mid-search
    lock_taken = False
    if not smoke:
        import bench

        lock_taken, holder = bench._try_acquire_tunnel_lock()
        if not lock_taken and holder is not None:
            print(json.dumps({
                "autotune": "blocked",
                "error": f"tunnel held by live session (pid {holder})",
            }))
            return 1
    try:
        measure = _subprocess_measure(
            payload_base, args.trial_timeout, verbose=True,
            # a real sweep's winner is an on-chip record: CPU-fallback
            # trials (tunnel down, no visible accelerator) must fail
            # rather than ledger CPU knobs under backend="tpu"
            require_accel=not smoke,
        )
        outcome = greedy_search(
            measure, base, space, max_trials=args.trials,
            log=lambda m: print(f"autotune: {m}", flush=True),
        )
    finally:
        if lock_taken:
            import bench

            try:
                os.remove(bench._TUNNEL_LOCK)
            except OSError:
                pass

    best = outcome.best
    summary = {
        "autotune": "ok" if best.ok else "FAILED",
        "metric": metric,
        "trials": outcome.trials,
        "pruned_knobs": outcome.pruned_knobs,
        "winner": best.to_dict(),
    }
    if best.ok and not args.no_persist:
        backend = "cpu" if smoke else "tpu"
        record = persist_winner(
            args.ledger, metric, outcome, backend=backend,
            extra={
                "workload": payload_base["workload"],
                **(
                    {"comm_shard_tier": args.comm_shard_tier}
                    if args.comm_shard_tier
                    else {}
                ),
            },
        )
        summary["persisted"] = {
            "ledger": args.ledger,
            "key": f"autotune/{metric}",
            "config_key": record["config_key"],
        }
    print(json.dumps(summary), flush=True)
    return 0 if best.ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Classifying tunnel probe: turn "probe failed/timed out" into data.

VERDICT r5 #5: three rounds of 10-minute watcher probes recorded only
"probe failed/timed out" — no distinction between TCP-unreachable, a
TCP-open-but-PJRT-handshake hang, or a backend-init error.  This probe
records an error CLASS per attempt so the outage distribution can be
summarized (BENCH_NOTES.md wedge characterization):

  classes:
    ok                   accelerator backend came up
    cpu-only             jax initialized but saw only the CPU backend
    tcp-refused          relay endpoint actively refused the connection
    tcp-timeout          relay endpoint did not complete the TCP handshake
    tcp-ok-probe-timeout TCP connects but the PJRT client hangs — the
                         single-client-relay wedge signature
    probe-timeout        PJRT probe hung and no endpoint is known to
                         separate relay-down from backend-down
    pjrt-error:<text>    backend init failed fast with an error
    import-error:<text>  jax import itself failed

The bare TCP liveness check needs no JAX (separates relay-down from
backend-down); the endpoint is taken from ``STOKE_TUNNEL_ENDPOINT``
(host:port) when the environment exports one — unset, the TCP half is
skipped and recorded as ``endpoint-unknown``.

Every attempt appends one JSON line to ``--log`` (default
/tmp/tunnel_probe_log.jsonl).  ``--summarize`` prints the class
distribution of the accumulated log — the multi-round evidence VERDICT
asked for.  Exit code: 0 when the accelerator is ALIVE, 1 otherwise
(drop-in for the watcher's inline probe).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

DEFAULT_LOG = "/tmp/tunnel_probe_log.jsonl"
PROBE_TIMEOUT = 120
TCP_TIMEOUT = 10


def tcp_liveness(endpoint: str | None) -> str:
    """Bare no-JAX TCP check of the relay endpoint."""
    if not endpoint or ":" not in endpoint:
        return "endpoint-unknown"
    host, _, port = endpoint.rpartition(":")
    try:
        with socket.create_connection((host, int(port)), timeout=TCP_TIMEOUT):
            return "tcp-ok"
    except ConnectionRefusedError:
        return "tcp-refused"
    except (socket.timeout, TimeoutError):
        return "tcp-timeout"
    except OSError as e:
        return f"tcp-error:{type(e).__name__}"


def jax_probe(timeout: int = PROBE_TIMEOUT) -> tuple[str, str]:
    """PJRT bring-up in a subprocess (a wedged tunnel hangs the import, so
    the parent must never import jax).  Returns (class, detail)."""
    code = (
        "import jax\n"
        "ds = jax.devices()\n"
        "print('BACKEND', jax.default_backend())\n"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return "probe-timeout", f"no PJRT response in {timeout}s"
    if out.returncode == 0:
        lines = (out.stdout or "").strip().splitlines()
        backend = lines[-1].split()[-1] if lines else ""
        if backend == "cpu":
            return "cpu-only", "jax up, CPU backend only"
        return "ok", f"backend={backend}"
    err = (out.stderr or "").strip().splitlines()
    detail = err[-1][:200] if err else "probe failed with no stderr"
    if "ImportError" in detail or "ModuleNotFoundError" in detail:
        return f"import-error:{detail[:80]}", detail
    return f"pjrt-error:{detail[:80]}", detail


def classify(endpoint: str | None) -> dict:
    tcp = tcp_liveness(endpoint)
    if tcp in ("tcp-refused", "tcp-timeout") or tcp.startswith("tcp-error"):
        # relay unreachable at the socket level: no point paying the
        # 120s PJRT timeout — the class IS the TCP failure
        return {"class": tcp, "tcp": tcp, "detail": "relay socket down"}
    cls, detail = jax_probe()
    if cls == "probe-timeout" and tcp == "tcp-ok":
        # the wedge signature: socket accepts, PJRT never answers
        cls = "tcp-ok-probe-timeout"
    return {"class": cls, "tcp": tcp, "detail": detail}


def summarize(log_path: str) -> dict:
    counts: dict = {}
    first = last = None
    try:
        with open(log_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                # aggregate by the class PREFIX: pjrt-error/import-error
                # classes embed truncated error text (kept per-attempt in
                # the log), which would fragment the distribution into
                # singleton buckets if counted verbatim
                cls = rec.get("class", "?").split(":", 1)[0]
                counts[cls] = counts.get(cls, 0) + 1
                first = first or rec.get("ts")
                last = rec.get("ts")
    except OSError:
        pass
    return {"probe_summary": counts, "attempts": sum(counts.values()),
            "first_ts": first, "last_ts": last}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--endpoint",
                    default=os.environ.get("STOKE_TUNNEL_ENDPOINT"),
                    help="relay host:port for the bare TCP check "
                    "(default: $STOKE_TUNNEL_ENDPOINT)")
    ap.add_argument("--log", default=DEFAULT_LOG,
                    help="JSONL attempt log (appended)")
    ap.add_argument("--summarize", action="store_true",
                    help="print the class distribution of the log and exit")
    args = ap.parse_args()
    if args.summarize:
        print(json.dumps(summarize(args.log)))
        return 0
    rec = classify(args.endpoint)
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(json.dumps(rec), flush=True)
    try:
        with open(args.log, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass
    return 0 if rec["class"] == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())

"""Pipeline-schedule characterization: measured bubble fraction and
steady-state utilization for the GPipe (rounds=1) and circular (rounds=V)
schedules, as a host-time proxy on the simulated CPU mesh.

The analytic model (parallel/pipeline.py docstring): a schedule with S
stages, V rounds, and M microbatches runs T = V*M + S - 1 ticks, of which
V*M do useful work per device — bubble = (S-1)/(V*M+S-1).  This script
checks the IMPLEMENTATION against that model: per-step wall time is
measured across an M sweep and regressed as t(M) = a*(V*M + S - 1) + c;
the fit recovering the analytic tick count (R^2 ~ 1, c small) means the
schedule executes with no hidden serialization, and measured utilization
V*M*a/t(M) tracks the analytic V*M/(V*M+S-1).

CPU-mesh caveat: all "devices" share host cores, so absolute times mean
nothing; the VALID signal is how time scales with M and V — i.e. the tick
count, which is schedule-determined, not hardware-determined.

Usage (hermetic, never touches the TPU tunnel):
    env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/bench_pipeline.py
"""

from __future__ import annotations

import json
import time

import numpy as np


def measure(rounds: int, Ms, S=4, B=16, D=256, reps=7):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from stoke_tpu.parallel.pipeline import pipeline, stack_stage_params

    devices = jax.devices("cpu")[:S]
    mesh = Mesh(np.asarray(devices), ("stage",))
    r = np.random.default_rng(0)
    L = rounds * S
    stacked = stack_stage_params([
        {"w": jnp.asarray(r.normal(size=(D, D)).astype(np.float32) * 0.1)}
        for _ in range(L)
    ])

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    piped = pipeline(stage_fn, mesh, "stage", rounds=rounds)
    step = jax.jit(lambda p, xs: piped(p, xs))

    rows = []
    for M in Ms:
        xs = jnp.asarray(r.normal(size=(M, B, D)).astype(np.float32))
        step(stacked, xs).block_until_ready()  # compile
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            step(stacked, xs).block_until_ready()
            ts.append(time.perf_counter() - t0)
        t = float(np.median(ts))
        ticks = rounds * M + S - 1
        rows.append({"M": M, "ticks": ticks, "t_ms": round(t * 1e3, 2)})
    # regress t = a*ticks + c
    ticks = np.array([row["ticks"] for row in rows], float)
    ts = np.array([row["t_ms"] for row in rows], float)
    A = np.vstack([ticks, np.ones_like(ticks)]).T
    (a, c), res, *_ = np.linalg.lstsq(A, ts, rcond=None)
    pred = A @ np.array([a, c])
    ss_tot = float(((ts - ts.mean()) ** 2).sum())
    r2 = 1.0 - float(((ts - pred) ** 2).sum()) / max(ss_tot, 1e-12)
    for row in rows:
        useful = rounds * row["M"]
        row["bubble_analytic"] = round((S - 1) / row["ticks"], 4)
        row["util_analytic"] = round(useful / row["ticks"], 4)
        row["util_measured"] = round(useful * a / row["t_ms"], 4)
    return {
        "rounds": rounds,
        "stages": S,
        "tick_ms_fit": round(float(a), 3),
        "overhead_ms_fit": round(float(c), 3),
        "r2": round(r2, 4),
        "rows": rows,
    }


def main():
    Ms = [4, 8, 16, 32, 64]
    out = {"schedules": []}
    for rounds in (1, 2, 4):
        res = measure(rounds, Ms)
        out["schedules"].append(res)
        print(json.dumps(res))
    # headline: does time scale with the analytic tick count?
    ok = all(s["r2"] > 0.98 for s in out["schedules"])
    print(json.dumps({
        "metric": "pipeline_schedule_tick_model_fit",
        "value": min(s["r2"] for s in out["schedules"]),
        "unit": "r2",
        "ok": ok,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env bash
# Multi-host smoke launch WITHOUT hardware: N local processes, each with K
# simulated CPU devices, rendezvousing over a localhost coordinator — the
# same code path a real TPU pod takes (docs/multihost.md "Testing multi-host
# paths without a pod"). Use this to validate your own multi-host training
# script on a laptop/CI before paying for a pod.
#
# Usage: scripts/launch_local.sh [-n NUM_PROCS] [-d DEVICES_PER_PROC] CMD...
#   CMD runs once per process with STOKE_PROCESS_ID / STOKE_NUM_PROCESSES /
#   JAX_COORDINATOR_ADDRESS exported; pass these to DistributedInitConfig
#   (or call jax.distributed.initialize yourself — before any other JAX API).
#
# Example (the in-repo worker used by tests/test_multiprocess.py):
#   scripts/launch_local.sh -n 2 -d 4 python tests/_mp_worker.py train_equiv /tmp/out
set -euo pipefail

NPROC=2
NDEV=4
while getopts "n:d:" opt; do
  case "$opt" in
    n) NPROC="$OPTARG" ;;
    d) NDEV="$OPTARG" ;;
    *) exit 1 ;;
  esac
done
shift $((OPTIND - 1))
[[ $# -gt 0 ]] || { echo "usage: $0 [-n N] [-d K] CMD..." >&2; exit 1; }

PORT=$(( (RANDOM % 20000) + 20000 ))
export JAX_COORDINATOR_ADDRESS="127.0.0.1:$PORT"
export STOKE_NUM_PROCESSES="$NPROC"
export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=$NDEV"

pids=()
cleanup() {
  for pid in "${pids[@]}"; do
    kill "$pid" 2>/dev/null || true
  done
}
trap cleanup EXIT INT TERM

for ((i = 1; i < NPROC; i++)); do
  STOKE_PROCESS_ID="$i" "$@" &
  pids+=($!)
done
status=0
# rank 0 failing must not orphan the workers (they would hang forever in
# rendezvous waiting for the dead coordinator) — collect its status, then
# wait for / reap everyone
STOKE_PROCESS_ID=0 "$@" || status=$?
if [[ "$status" -ne 0 ]]; then
  # coordinator died: workers would block in rendezvous forever
  cleanup
fi
for pid in "${pids[@]}"; do
  wait "$pid" || status=$?
done
pids=()
exit "$status"

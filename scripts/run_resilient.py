"""Supervised restarts with backoff: the restart half of ISSUE 7's
detect→save→restart→resume loop.

Runs a training command under a bounded restart loop:

    python scripts/run_resilient.py --max-restarts 8 --record restarts.jsonl \
        -- python my_train.py --flags...

- **Classification**: the worker's exit code decides the next move.
  ``0`` = done.  The resumable codes — the health watchdog's 113 ("hung
  and self-killed"; a fresh process usually un-wedges it), the preemption
  drain's 114 ("emergency checkpoint written"), and signal deaths
  (negative returncodes: SIGKILL'd by a preempted VM or the OOM killer) —
  restart after a backoff.  Everything else (including a generic python
  crash, e.g. a status-validation error) is FATAL: restarting a
  deterministic bug burns the restart budget without ever progressing.
- **Backoff**: exponential with jitter (``RestartBackoff`` — a fleet of
  preempted workers must not restart in lockstep) and a restart budget.
- **Records**: one JSONL line per attempt (exit code, classification,
  backoff delay, flight-recorder bundle paths via the
  ``STOKE_HEALTH_BUNDLE_FILE`` handshake, and — when a bundle carries a
  ``fleet.json`` — the fleet straggler verdict, so the restart record
  shows WHY the host died, not just that it did).
- **Attempt number**: each restart runs with ``STOKE_RESTART_ATTEMPT=<n>``
  so the worker's ``resilience/restarts`` gauge and JSONL column reflect
  the supervision history.

The worker is expected to call ``Stoke.resume()`` at startup (or
``maybe_resume``) so a restart continues from the emergency checkpoint
instead of step 0 — see docs/multihost.md "Surviving preemption".

Like ``scripts/_supervise.py`` and ``scripts/autotune.py``, this process
NEVER imports jax (a wedged TPU tunnel hangs any process at backend init):
the jax-free resilience primitives are loaded from
``stoke_tpu/resilience.py`` by FILE, bypassing the package ``__init__``
whose facade import would pull jax in.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import random
import subprocess
import sys
import tempfile
import time
from typing import Any, Callable, Dict, Optional, Sequence

_HERE = os.path.dirname(os.path.abspath(__file__))
_RESILIENCE_PY = os.path.join(
    os.path.dirname(_HERE), "stoke_tpu", "resilience.py"
)

# the recorder handshake (BUNDLE_FILE_ENV + bundle-file reader) lives in the
# sibling jax-free supervisor module — one definition, not three
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)
from _supervise import BUNDLE_FILE_ENV, _read_bundles  # noqa: E402


def load_resilience():
    """The jax-free resilience primitives (RestartBackoff, classify_exit,
    RESTART_ATTEMPT_ENV, ...) loaded by file path — the package __init__
    imports the facade, which imports jax."""
    spec = importlib.util.spec_from_file_location(
        "_stoke_resilience_supervisor", _RESILIENCE_PY
    )
    mod = importlib.util.module_from_spec(spec)
    # registered BEFORE exec: the @dataclass decorator inside resolves its
    # defining module through sys.modules at class-creation time
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _lost_goodput_estimate(bundles: Sequence[str]) -> Optional[dict]:
    """Restart-cost estimate from the NEWEST bundle carrying the
    preemption dump's accounting (ISSUE 14 satellite): the dying worker
    stamps ``step_ema_s`` (host-wall EMA of one optimizer step) and
    ``lost_steps_estimate`` (0 when the emergency save landed; steps since
    the last durable save when it failed) into the bundle manifest — their
    product prices the attempt's lost goodput in seconds without replaying
    any JSONL.  None when no bundle carries the fields."""
    for bundle in reversed(list(bundles)):
        try:
            with open(os.path.join(bundle, "manifest.json")) as f:
                extra = (json.load(f) or {}).get("extra") or {}
        except (OSError, ValueError):
            continue
        lost = extra.get("lost_steps_estimate")
        ema = extra.get("step_ema_s")
        if lost is None:
            continue
        out = {"lost_steps_estimate": int(lost)}
        if ema is not None:
            out["step_ema_s"] = round(float(ema), 6)
            out["lost_goodput_s_est"] = round(int(lost) * float(ema), 3)
        return out
    return None


def _fleet_verdict(bundles: Sequence[str]) -> Optional[dict]:
    """The fleet straggler verdict of the NEWEST bundle carrying one
    (ISSUE 5's fleet.json) — surfaces WHY the host died in the restart
    record.  None when no bundle has a fleet view."""
    for bundle in reversed(list(bundles)):
        try:
            with open(os.path.join(bundle, "fleet.json")) as f:
                fleet = json.load(f)
        except (OSError, ValueError):
            continue
        verdict = fleet.get("verdict") or fleet.get("last_verdict")
        if verdict:
            return verdict
    return None


def _default_run(argv: Sequence[str], env: Dict[str, str]) -> int:
    """Run one worker attempt to completion, relaying its streams."""
    proc = subprocess.Popen(list(argv), env=env)
    return proc.wait()


def run_resilient(
    argv: Sequence[str],
    *,
    max_restarts: int = 8,
    base_s: float = 1.0,
    factor: float = 2.0,
    max_s: float = 60.0,
    jitter_frac: float = 0.5,
    extra_resumable: Sequence[int] = (),
    record_path: Optional[str] = None,
    seed: Optional[int] = None,
    env: Optional[Dict[str, str]] = None,
    run: Callable[[Sequence[str], Dict[str, str]], int] = _default_run,
    sleep: Callable[[float], None] = time.sleep,
) -> Dict[str, Any]:
    """Drive ``argv`` under the bounded restart loop; returns a summary
    dict (``ok`` / ``fatal`` / ``exhausted``, attempts, records).

    ``run`` and ``sleep`` are injectable so the backoff/budget tests run
    deterministic and instantaneous (no subprocesses, no real sleeps);
    ``seed`` pins the jitter rng.
    """
    rz = load_resilience()
    backoff = rz.RestartBackoff(
        base_s=base_s,
        factor=factor,
        max_s=max_s,
        jitter_frac=jitter_frac,
        max_restarts=max_restarts,
        rng=random.Random(seed) if seed is not None else None,
    )
    records = []
    attempt = 0
    outcome: Dict[str, Any] = {"ok": False}
    while True:
        bundle_fd, bundle_file = tempfile.mkstemp(prefix="stoke-bundles-")
        os.close(bundle_fd)
        attempt_env = {
            **(env if env is not None else os.environ),
            rz.RESTART_ATTEMPT_ENV: str(attempt),
            BUNDLE_FILE_ENV: bundle_file,
        }
        t0 = time.monotonic()
        code = run(argv, attempt_env)
        elapsed_s = time.monotonic() - t0
        bundles = _read_bundles(bundle_file)
        try:
            os.remove(bundle_file)
        except OSError:
            pass
        classification = rz.classify_exit(code, extra_resumable)
        record = {
            "attempt": attempt,
            "exit_code": code,
            "class": classification,
            # restart cost, readable straight off the record (ISSUE 14):
            # attempt wall clock + the bundle-priced lost-goodput estimate
            "elapsed_s": round(elapsed_s, 3),
            "bundles": bundles,
            "restarts_used": backoff.restarts_used,
        }
        cost = _lost_goodput_estimate(bundles)
        if cost is not None:
            record.update(cost)
        verdict = _fleet_verdict(bundles)
        if verdict is not None:
            record["fleet_verdict"] = verdict
        if classification == "ok":
            outcome = {"ok": True}
        elif classification == "fatal":
            outcome = {"ok": False, "fatal": True, "exit_code": code}
        else:
            delay = backoff.next_delay()
            if delay is None:
                outcome = {
                    "ok": False,
                    "exhausted": True,
                    "exit_code": code,
                    "max_restarts": max_restarts,
                }
            else:
                record["backoff_s"] = round(delay, 3)
        records.append(record)
        if record_path:
            with open(record_path, "a") as f:
                f.write(json.dumps(record) + "\n")
        sys.stderr.write(
            f"run_resilient: attempt {attempt} exited {code} "
            f"({classification})"
            + (f"; restarting in {record['backoff_s']}s" if "backoff_s" in record else "")
            + "\n"
        )
        if "backoff_s" not in record:
            break
        sleep(record["backoff_s"])
        attempt += 1
    outcome["attempts"] = attempt + 1
    outcome["restarts"] = attempt
    outcome["records"] = records
    return outcome


def main() -> int:
    ap = argparse.ArgumentParser(
        description="bounded restart supervisor (ISSUE 7): restarts "
        "resumable worker deaths (preemption 114 / watchdog 113 / signal "
        "kills) with exponential backoff; fatal exits stop immediately",
    )
    ap.add_argument("--max-restarts", type=int, default=8)
    ap.add_argument("--base-s", type=float, default=1.0,
                    help="first backoff delay (doubles per restart)")
    ap.add_argument("--max-s", type=float, default=60.0,
                    help="backoff ceiling")
    ap.add_argument("--jitter-frac", type=float, default=0.5,
                    help="additive-uniform jitter as a fraction of the "
                    "delay (de-synchronizes fleet restarts)")
    ap.add_argument("--extra-resumable", type=int, nargs="*", default=[],
                    help="additional exit codes to classify as resumable")
    ap.add_argument("--record", default=None,
                    help="append one JSONL restart record per attempt here")
    ap.add_argument("--seed", type=int, default=None,
                    help="pin the jitter rng (tests)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="worker command (prefix with --)")
    args = ap.parse_args()
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no worker command given (append: -- python train.py ...)")
    outcome = run_resilient(
        cmd,
        max_restarts=args.max_restarts,
        base_s=args.base_s,
        max_s=args.max_s,
        jitter_frac=args.jitter_frac,
        extra_resumable=args.extra_resumable,
        record_path=args.record,
        seed=args.seed,
    )
    summary = {k: v for k, v in outcome.items() if k != "records"}
    print(json.dumps({"run_resilient": summary}))
    if outcome.get("ok"):
        return 0
    # surface the worker's own fatal code where there is one (a wrapper
    # swallowing exit codes makes outer supervisors blind); signal deaths
    # map to the shell convention 128+signum — a raw negative status
    # truncates mod 256 into a meaningless code
    code = int(outcome.get("exit_code") or 1)
    return 128 - code if code < 0 else code


if __name__ == "__main__":
    sys.exit(main())

"""Align two runs' telemetry JSONL by step and print the per-layer drift.

The offline half of the per-layer numerics observatory (ISSUE 12): two
runs with ``NumericsConfig(per_group_jsonl=True)`` leave ``numerics/
per_group`` blocks in their ``steps.jsonl``; this tool aligns the two
streams by optimizer step and prints, per module group, how far run B's
per-layer statistics drift from run A's — the fp32-vs-int8 quality
bisection ("which layer does the quantized wire hurt?") and the
run-vs-run divergence bisection ("which layer moved first?") in one
table.  Pure file work; never touches an accelerator.

Usage (CPU-safe):

    env PYTHONPATH=. JAX_PLATFORMS=cpu \
        python scripts/numerics_diff.py <run_a> <run_b> [--json]
        [--stat grad_rms] [--top 0] [--no-validate]

``<run>`` is a telemetry output dir (``steps.jsonl`` / rank-0 stream
inside) or an explicit jsonl file.  Drift per group is reported at the
LAST aligned step (where divergence is largest) plus the worst step seen;
``rel`` is ``|b - a| / (|a| + eps)``.  Exit 0 on a clean diff, 2 when the
streams share no step carrying a per-group block on both sides —
"nothing aligned", mirroring ``merge_rank_jsonl.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_EPS = 1e-12


def resolve_stream(path: str) -> str:
    """A run dir resolves to its ``steps.jsonl`` (or the rank-0 stream of
    an all-ranks run); an explicit file passes through."""
    if os.path.isdir(path):
        for name in ("steps.jsonl", "steps.rank0.jsonl"):
            candidate = os.path.join(path, name)
            if os.path.exists(candidate):
                return candidate
        raise FileNotFoundError(
            f"{path}: no steps.jsonl / steps.rank0.jsonl inside"
        )
    return path


def load_numerics(
    path: str, validate: bool
) -> Dict[int, Dict[str, Dict[str, float]]]:
    """``{step: per_group_block}`` for records carrying one."""
    from stoke_tpu.telemetry.events import read_step_events

    out: Dict[int, Dict[str, Dict[str, float]]] = {}
    for rec in read_step_events(path, validate=validate):
        block = rec.get("numerics/per_group")
        if block:
            out[int(rec["step"])] = block
    return out


def diff_runs(
    a: Dict[int, Dict[str, Dict[str, float]]],
    b: Dict[int, Dict[str, Dict[str, float]]],
    stat: str,
) -> Dict[str, Any]:
    """Per-group drift over the aligned steps.

    Groups present in only one run are reported (``only_in``) rather than
    silently dropped — a missing group IS the drift when comparing a
    refactored model.  Per aligned group: the compared stat's values and
    relative drift at the last aligned step, and the worst drift over all
    aligned steps (with the step it peaked at).
    """
    steps = sorted(set(a) & set(b))
    groups_a = set().union(*(set(v) for v in a.values())) if a else set()
    groups_b = set().union(*(set(v) for v in b.values())) if b else set()
    shared = sorted(groups_a & groups_b)
    rows: List[Dict[str, Any]] = []
    for group in shared:
        last = None
        worst: Optional[Tuple[float, int, float, float]] = None
        for step in steps:
            va = (a[step].get(group) or {}).get(stat)
            vb = (b[step].get(group) or {}).get(stat)
            if va is None or vb is None:
                continue
            rel = abs(vb - va) / (abs(va) + _EPS)
            last = {"step": step, "a": va, "b": vb, "rel": rel}
            if worst is None or rel > worst[0]:
                worst = (rel, step, va, vb)
        if last is None:
            continue
        rows.append({
            "group": group,
            "last_step": last["step"],
            "a": last["a"],
            "b": last["b"],
            "rel": last["rel"],
            "worst_rel": worst[0],
            "worst_step": worst[1],
        })
    rows.sort(key=lambda r: r["worst_rel"], reverse=True)
    return {
        "stat": stat,
        "aligned_steps": len(steps),
        "steps": steps,
        "groups": shared,
        "only_in_a": sorted(groups_a - groups_b),
        "only_in_b": sorted(groups_b - groups_a),
        "rows": rows,
    }


def print_table(report: Dict[str, Any], top: int) -> None:
    stat = report["stat"]
    hdr = (
        f"{'group':<24} {'a:' + stat:>14} {'b:' + stat:>14} "
        f"{'rel_drift':>10} {'worst':>10} {'@step':>6}"
    )
    print(hdr)
    print("-" * len(hdr))
    rows = report["rows"][:top] if top else report["rows"]
    for r in rows:
        print(
            f"{r['group']:<24} {r['a']:>14.6g} {r['b']:>14.6g} "
            f"{100 * r['rel']:>9.2f}% {100 * r['worst_rel']:>9.2f}% "
            f"{r['worst_step']:>6}"
        )
    print()
    print(
        f"{report['aligned_steps']} aligned steps, "
        f"{len(report['groups'])} shared groups"
    )
    for side in ("a", "b"):
        only = report[f"only_in_{side}"]
        if only:
            print(f"  groups only in run {side}: {', '.join(only)}")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="align two runs' numerics/per_group JSONL blocks by "
        "step and print the per-layer drift table (fp32-vs-int8 or "
        "run-vs-run bisection)"
    )
    ap.add_argument("run_a", help="telemetry output dir or jsonl file")
    ap.add_argument("run_b", help="telemetry output dir or jsonl file")
    ap.add_argument("--stat", default="grad_rms",
                    help="per-group stat to diff (grad_rms, grad_absmax, "
                    "param_rms, update_rms, nonfinite, wire_err, "
                    "quant_err; default grad_rms)")
    ap.add_argument("--top", type=int, default=0,
                    help="print only the N worst-drifting groups "
                    "(0 = all)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as one JSON document")
    ap.add_argument("--no-validate", action="store_true",
                    help="skip step-event schema validation (salvaging "
                    "truncated streams from dead runs)")
    args = ap.parse_args(argv)

    streams = []
    for path in (args.run_a, args.run_b):
        try:
            resolved = resolve_stream(path)
            streams.append(load_numerics(resolved, not args.no_validate))
        except (OSError, ValueError) as e:
            print(f"{path}: {e}", file=sys.stderr)
            return 2
    a, b = streams
    report = diff_runs(a, b, args.stat)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print_table(report, args.top)
    if report["aligned_steps"] == 0 or not report["rows"]:
        # no step carries a per-group block in BOTH streams (disjoint
        # cadences, numerics off in one run, or the requested stat absent
        # everywhere) — "nothing could be aligned" is the documented
        # nonzero-exit condition, mirroring merge_rank_jsonl.py
        print(
            "no step carries a numerics/per_group block (with the "
            "requested stat) in both runs; nothing aligned",
            file=sys.stderr,
        )
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())

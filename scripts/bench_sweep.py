"""Batch-size / step-API sweep for the CIFAR-10 ResNet-50 TPU benchmark.

Runs serially in ONE process (the remote-TPU tunnel is single-client) and
prints one JSON line per configuration.  Delta timing as in bench.py.

Tunnel discipline (BENCH_NOTES.md): a supervisor process (never imports
jax) pre-probes the device with a timeout and runs the measurement in a
watchdogged subprocess, so a wedged tunnel yields an error line instead of
a hang — same hardening as bench.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _supervise import supervise  # noqa: E402


def build(batch):
    import jax
    import optax

    from stoke_tpu import Stoke, StokeOptimizer
    from stoke_tpu.models import ResNet50
    from stoke_tpu.utils import init_module

    model = ResNet50(num_classes=10, cifar_stem=True)
    variables = init_module(
        model, jax.random.PRNGKey(0), np.zeros((2, 32, 32, 3), np.float32), train=False
    )
    on_accel = jax.default_backend() not in ("cpu",)
    return Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.05, "momentum": 0.9}
        ),
        loss=lambda logits, labels: optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean(),
        params=variables,
        batch_size_per_device=batch,
        device="tpu" if on_accel else "cpu",
        precision="bf16",
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        verbose=False,
    )


def measure(stoke, batch, api, steps=30, warmup=5):
    import jax

    r = np.random.default_rng(0)
    if api == "train_steps":
        # multi-step scan: SEG optimizer steps per dispatch, stacked inputs
        SEG = 10
        xs = jax.device_put(
            r.normal(size=(SEG, batch, 32, 32, 3)).astype(np.float32)
        )
        ys = jax.device_put(r.integers(0, 10, size=(SEG, batch)))

        def one_step(i):
            return stoke.train_steps(xs, (ys,))

        per_call = SEG
    else:
        pool = [
            (
                jax.device_put(r.normal(size=(batch, 32, 32, 3)).astype(np.float32)),
                jax.device_put(r.integers(0, 10, size=(batch,))),
            )
            for _ in range(4)
        ]

        def one_step(i):
            x, y = pool[i % len(pool)]
            if api == "train_step":
                return stoke.train_step(x, (y,))
            out = stoke.model(x)
            loss = stoke.loss(out, y)
            stoke.backward(loss)
            stoke.step()
            return loss

        per_call = 1

    def timed(n):
        t0 = time.perf_counter()
        last = None
        for i in range(n):
            last = one_step(i)
        np.asarray(jax.tree_util.tree_leaves(last)[0])
        return time.perf_counter() - t0

    for i in range(warmup):
        one_step(i)
    timed(1)
    t1 = timed(steps)
    t2 = timed(2 * steps)
    dt = max(t2 - t1, 1e-9)
    return batch * steps * per_call / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--_worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--batches", default="256,512,1024")
    ap.add_argument("--apis", default="4call,train_step,train_steps")
    ap.add_argument("--steps", type=int, default=None)
    args = ap.parse_args()
    if not args._worker:
        sys.exit(supervise(__file__, sys.argv[1:]))
    results = []
    for batch in (int(b) for b in args.batches.split(",")):
        for api in args.apis.split(","):
            stoke = build(batch)
            kw = {"steps": args.steps} if args.steps else {}
            if api == "train_steps":
                # each call is already 10 steps; fewer outer reps needed
                kw = {"steps": max(3, (args.steps or 30) // 10), "warmup": 1}
            ips = measure(stoke, batch, api, **kw)
            rec = {"batch": batch, "api": api, "imgs_per_sec": round(ips, 1)}
            print(json.dumps(rec), flush=True)
            results.append(rec)
            del stoke
    best = max(results, key=lambda r: r["imgs_per_sec"])
    print(json.dumps({"best": best}))


if __name__ == "__main__":
    main()

"""Shared delta-timing rig for the on-TPU measurement scripts.

(t(2n) - t(n)) / n cancels the fixed host/tunnel sync overhead that a
remote device adds to every fetch.  Two rules this module enforces that
hand-rolled copies kept getting wrong:

- BLOCK after warmup (async dispatch otherwise bleeds queued warmup
  executions into the first timed segment);
- sync on a SCALAR element, not the full output (np.asarray on a jax
  array fetches the whole buffer — 128 MB for an 8k x 8k bf16 matmul —
  through the single-client tunnel).
"""

from __future__ import annotations

import time


def sync(out) -> None:
    """Force completion of ``out`` by fetching one scalar element."""
    import jax
    import numpy as np

    leaf = jax.tree_util.tree_leaves(out)[0]
    idx = (0,) * getattr(leaf, "ndim", 0)
    np.asarray(leaf[idx] if idx else leaf)


def delta_time(fn, reps: int) -> float:
    """Per-call seconds of ``fn()`` via delta timing (compile + warm first)."""
    fn()          # compile
    sync(fn())    # warm, and drain the queue before t0
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = fn()
    sync(out)
    t1 = time.perf_counter()
    for _ in range(2 * reps):
        out = fn()
    sync(out)
    return max((time.perf_counter() - t1) - (t1 - t0), 1e-9) / reps

"""Invariant linter: the repo's codified disciplines as machine-checked
rules (ISSUE 15 tentpole, linter half).

Every subsystem since PR 1 hand-writes the same correctness guards as
per-PR tests — append-only wire formats, "every config knob reachable by
a status rule", nullable JSONL fields, no ``device_get`` on hot paths,
jax-free driver modules — so each NEW PR can silently break a discipline
no test yet covers.  This module turns those conventions into static
checks over the source tree:

- **Wire-format append-only** (``wire-append-only``): the packed-vector
  layouts that cross process or version boundaries (``SENTINEL_FIELDS``,
  ``FLEET_SIGNALS``, ``NUMERICS_STATS``) are pinned in a committed
  manifest (``analysis/manifests/wire_formats.json``); the lint fails on
  any reorder/remove/insert, and on an append that did not update the
  manifest in the same PR (the manifest IS the reviewed wire contract).
- **Config-field status-rule coverage** (``config-guard``): every
  dataclass field in ``configs.py`` must be *reachable* from the
  validation layer — its name read as an attribute or named as an
  identifier string in ``status.py`` (or in ``configs.py``'s own
  resolver functions, e.g. ``comm_shard_updates``) — or explicitly
  waived with a reason in ``analysis/manifests/config_waivers.json``.
  The silently-ignored-knob anti-pattern, re-litigated in every PR
  since 2, becomes a lint failure.  Unknown waiver entries are
  themselves findings (``config-waiver-unknown``) — a stale waiver must
  not shadow a real regression.
- **Nullable-JSONL discipline** (``jsonl-schema``): every namespaced
  step-event key a subsystem's ``event_fields`` emitter can produce
  must exist in ``events.py``'s ``STEP_EVENT_FIELDS`` with a nullable,
  non-required kind (conditionally-emitted keys that the schema does
  not know are exactly how a dashboard breaks at 3am).
- **Banned APIs** (``banned-jax-import`` / ``banned-device-get``):
  module-scope ``jax``/``jaxlib`` imports in the jax-free modules (the
  supervisor/autotune/lint drivers a wedged TPU tunnel must never hang
  at backend init — including this linter's own CLI), and
  ``device_get`` anywhere in the engine/serving hot paths (the
  zero-extra-dispatch sentinel discipline: diagnostics ride the
  compiled programs or the telemetry cadence, never a per-dispatch
  fetch).

Deliberately **jax-free and AST-based** (stdlib only: ``ast``, ``json``,
``os``, ``dataclasses``) so ``scripts/stoke_lint.py`` can load this file
directly (by FILE, bypassing the package ``__init__`` whose facade
import would pull jax in — the ``scripts/autotune.py`` discipline) and
run in CI before any backend exists.  The jax-dependent half — the
program auditor over lowered jaxpr/HLO step programs — lives in
:mod:`stoke_tpu.analysis.program` and shares this module's
:class:`Finding` type.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: linter identity, stamped into --json output
LINT_VERSION = "stoke_tpu.analysis/v1"

#: committed manifests (repo-relative)
WIRE_MANIFEST_PATH = "stoke_tpu/analysis/manifests/wire_formats.json"
CONFIG_WAIVERS_PATH = "stoke_tpu/analysis/manifests/config_waivers.json"

#: the config/validation pair the coverage rule reads
CONFIGS_PATH = "stoke_tpu/configs.py"
STATUS_PATH = "stoke_tpu/status.py"
#: the step-event schema the JSONL rule reads
EVENTS_SCHEMA_PATH = "stoke_tpu/telemetry/events.py"

#: modules that must never import jax/jaxlib at MODULE scope (the
#: supervisors and drivers that must stay runnable while a TPU tunnel is
#: wedged; function-local imports are fine — resilience.py's contract)
JAX_FREE_MODULES: Tuple[str, ...] = (
    "stoke_tpu/autotune.py",
    "stoke_tpu/resilience.py",
    "stoke_tpu/analysis/invariants.py",  # the CLI loads THIS in-process
    "scripts/run_resilient.py",
    "scripts/_supervise.py",
    "scripts/stoke_lint.py",
)

#: hot-path modules where ``device_get`` is banned outright (fetches ride
#: the sentinel row / telemetry cadence instead — PR 3's discipline)
DEVICE_GET_BANNED_MODULES: Tuple[str, ...] = (
    "stoke_tpu/engine.py",
    "stoke_tpu/serving/engine.py",
)

#: modules whose ``event_fields``-family functions emit namespaced JSONL
#: keys conditionally (the nullable-block discipline)
JSONL_EMITTER_MODULES: Tuple[str, ...] = (
    "stoke_tpu/telemetry/fleet.py",
    "stoke_tpu/telemetry/numerics.py",
    "stoke_tpu/resilience.py",
    "stoke_tpu/serving/telemetry.py",
    "stoke_tpu/serving/slo.py",
    "stoke_tpu/serving/roofline.py",
    "stoke_tpu/telemetry/memory.py",
)
#: emitter function names the JSONL rule inspects
_JSONL_EMITTER_FNS = ("event_fields", "_event_fields", "_base_event_fields")
#: namespaced key prefixes that identify a conditionally-emitted field
_JSONL_NAMESPACES = ("fleet/", "resilience/", "serve/", "numerics/", "mem/")


@dataclass
class Finding:
    """One lint/audit violation: where, which rule, and — always — the
    remedy, named the way status.py rules name theirs.  Shared by the
    jax-free linter and the jax-side program auditor (whose findings use
    a ``<jit:program>`` pseudo-file and line 0)."""

    rule: str
    file: str
    line: int
    message: str
    remedy: str
    severity: str = "error"

    def format(self) -> str:
        return (
            f"{self.file}:{self.line}: [{self.rule}] {self.message} "
            f"— remedy: {self.remedy}"
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "message": self.message,
            "remedy": self.remedy,
            "severity": self.severity,
        }


# --------------------------------------------------------------------------- #
# shared AST helpers
# --------------------------------------------------------------------------- #


def _parse(path: str) -> ast.Module:
    with open(path, "r") as f:
        return ast.parse(f.read(), filename=path)


def _rel(repo_root: str, path: str) -> str:
    try:
        return os.path.relpath(path, repo_root)
    except ValueError:
        return path


def _find_tuple_assign(
    tree: ast.Module, name: str
) -> Optional[Tuple[List[str], int]]:
    """Top-level ``NAME = ("a", "b", ...)`` → (fields, lineno); None when
    the symbol is missing or not a literal string tuple/list."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if name not in targets:
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in node.value.elts
            ):
                return (
                    [e.value for e in node.value.elts],
                    node.lineno,
                )
            return None
    return None


def _module_scope_walk(tree: ast.Module):
    """Yield nodes reachable WITHOUT entering a function/lambda body —
    module scope including ``if``/``try`` blocks, which is exactly where
    an eager import hides."""
    stack: List[ast.AST] = [tree]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
            ):
                continue
            stack.append(child)


# --------------------------------------------------------------------------- #
# rule: wire-format append-only
# --------------------------------------------------------------------------- #


def load_wire_manifest(repo_root: str) -> Optional[List[Dict[str, Any]]]:
    path = os.path.join(repo_root, WIRE_MANIFEST_PATH)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)["wire_formats"]


def check_wire_formats(
    repo_root: str,
    manifest: Optional[Sequence[Dict[str, Any]]] = None,
) -> List[Finding]:
    """Append-only wire formats: for each manifest entry ``{file, name,
    fields}``, the committed field list must be a PREFIX of the current
    tuple (reorder/remove/insert between pinned fields = a host on the
    old code misreads every later slot), and the current tuple must not
    have grown past the manifest without the manifest growing with it
    (the manifest is the reviewed contract, not a cache)."""
    findings: List[Finding] = []
    if manifest is None:
        manifest = load_wire_manifest(repo_root)
        if manifest is None:
            return [
                Finding(
                    rule="wire-append-only",
                    file=WIRE_MANIFEST_PATH,
                    line=0,
                    message="wire-format manifest is missing",
                    remedy=(
                        "commit analysis/manifests/wire_formats.json "
                        "seeded from the current SENTINEL_FIELDS / "
                        "FLEET_SIGNALS / NUMERICS_STATS tuples"
                    ),
                )
            ]
    for entry in manifest:
        rel = entry["file"]
        name = entry["name"]
        pinned = list(entry["fields"])
        path = os.path.join(repo_root, rel)
        if not os.path.exists(path):
            findings.append(
                Finding(
                    rule="wire-append-only",
                    file=rel,
                    line=0,
                    message=f"wire-format module {rel!r} not found",
                    remedy=(
                        f"restore the module or update the {name} entry "
                        f"in {WIRE_MANIFEST_PATH}"
                    ),
                )
            )
            continue
        found = _find_tuple_assign(_parse(path), name)
        if found is None:
            findings.append(
                Finding(
                    rule="wire-append-only",
                    file=rel,
                    line=0,
                    message=(
                        f"{name} is not a top-level literal string tuple "
                        f"(the lintable wire-format form)"
                    ),
                    remedy=(
                        f"keep {name} a module-level tuple of string "
                        f"literals so the append-only check can read it"
                    ),
                )
            )
            continue
        current, line = found
        if current[: len(pinned)] != pinned:
            # name the first divergent slot — that is the field a host on
            # the old layout would misread
            idx = next(
                (
                    i
                    for i, p in enumerate(pinned)
                    if i >= len(current) or current[i] != p
                ),
                0,
            )
            got = current[idx] if idx < len(current) else "<removed>"
            findings.append(
                Finding(
                    rule="wire-append-only",
                    file=rel,
                    line=line,
                    message=(
                        f"{name} is a wire format and its committed "
                        f"layout was reordered/removed: slot {idx} is "
                        f"pinned to {pinned[idx]!r} but the tree has "
                        f"{got!r} (hosts on mixed code versions would "
                        f"silently misread every later slot)"
                    ),
                    remedy=(
                        f"never reorder or remove {name} entries — "
                        f"append new fields at the end and keep old "
                        f"slots in place (docs/analysis.md, "
                        f"'append-only wire formats')"
                    ),
                )
            )
        elif len(current) > len(pinned):
            extra = current[len(pinned):]
            findings.append(
                Finding(
                    rule="wire-append-only",
                    file=rel,
                    line=line,
                    message=(
                        f"{name} grew {extra} past the committed "
                        f"manifest (append is legal but must be "
                        f"reviewed as a wire-format change)"
                    ),
                    remedy=(
                        f"append {extra} to the {name} entry in "
                        f"{WIRE_MANIFEST_PATH} in the same PR"
                    ),
                )
            )
    return findings


# --------------------------------------------------------------------------- #
# rule: config-field status coverage
# --------------------------------------------------------------------------- #


def _dataclass_fields(
    tree: ast.Module,
) -> Dict[str, List[Tuple[str, int]]]:
    """``{class_name: [(field, lineno), ...]}`` for every @dataclass in
    the module."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        if not any(
            (isinstance(d, ast.Name) and d.id == "dataclass")
            or (isinstance(d, ast.Attribute) and d.attr == "dataclass")
            or (
                isinstance(d, ast.Call)
                and (
                    (
                        isinstance(d.func, ast.Name)
                        and d.func.id == "dataclass"
                    )
                    or (
                        isinstance(d.func, ast.Attribute)
                        and d.func.attr == "dataclass"
                    )
                )
            )
            for d in node.decorator_list
        ):
            continue
        fields = [
            (stmt.target.id, stmt.lineno)
            for stmt in node.body
            if isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
        ]
        out[node.name] = fields
    return out


def _guarded_names(status_tree: ast.Module, configs_tree: ast.Module) -> set:
    """Names the validation layer can 'reach': attribute accesses on
    simple names (``cfg.dtype`` — NOT call results, string methods, or
    dotted modules like ``os.path.join``, whose ``.join``/``.get``
    would silently 'cover' any config field sharing a common method
    name) and identifier string constants (the ``getattr(cfg, name)``
    loop form) anywhere in status.py, plus the same inside configs.py's
    module-level FUNCTIONS (the resolver-function allowance —
    ``comm_shard_updates`` is the single source of truth status rules
    call into, so the fields it reads are guarded)."""

    def _collect(nodes, names):
        for node in nodes:
            if isinstance(node, ast.Attribute):
                # Name base: cfg.dtype; Subscript base: the rule-table
                # s["grad_clip"].clip_value form.  Calls, string
                # literals, and dotted modules stay excluded.
                if isinstance(node.value, (ast.Name, ast.Subscript)):
                    names.add(node.attr)
            elif isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                if node.value.isidentifier():
                    names.add(node.value)

    names: set = set()
    _collect(ast.walk(status_tree), names)
    for node in configs_tree.body:
        if isinstance(node, ast.FunctionDef):
            _collect(ast.walk(node), names)
    return names


def load_config_waivers(repo_root: str) -> Optional[Dict[str, str]]:
    path = os.path.join(repo_root, CONFIG_WAIVERS_PATH)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)["waivers"]


def check_config_coverage(
    repo_root: str,
    configs_path: Optional[str] = None,
    status_path: Optional[str] = None,
    waivers: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    """Every dataclass field in configs.py must be reachable from a
    status.py rule (attribute access or identifier-string reference) or
    explicitly waived with a reason.  Waiver entries naming a class or
    field that does not exist are findings themselves — a stale waiver
    silently re-opens the hole it once documented."""
    findings: List[Finding] = []
    configs_path = configs_path or os.path.join(repo_root, CONFIGS_PATH)
    status_path = status_path or os.path.join(repo_root, STATUS_PATH)
    if waivers is None:
        waivers = load_config_waivers(repo_root)
        if waivers is None:
            return [
                Finding(
                    rule="config-guard",
                    file=CONFIG_WAIVERS_PATH,
                    line=0,
                    message="config-waiver manifest is missing",
                    remedy=(
                        "commit analysis/manifests/config_waivers.json "
                        "({\"waivers\": {\"Class.field\": \"reason\"}})"
                    ),
                )
            ]
    configs_tree = _parse(configs_path)
    status_tree = _parse(status_path)
    classes = _dataclass_fields(configs_tree)
    guarded = _guarded_names(status_tree, configs_tree)
    configs_rel = _rel(repo_root, configs_path)

    # loud waiver validation first: unknown entries are findings
    for key, reason in waivers.items():
        cls, _, fname = key.partition(".")
        known = cls in classes and fname in {f for f, _ in classes[cls]}
        if not known:
            findings.append(
                Finding(
                    rule="config-waiver-unknown",
                    file=CONFIG_WAIVERS_PATH,
                    line=0,
                    message=(
                        f"waiver names unknown config field {key!r} "
                        f"(reason on file: {reason!r})"
                    ),
                    remedy=(
                        "remove the stale waiver entry or fix its "
                        "Class.field spelling — a waiver that matches "
                        "nothing guards nothing"
                    ),
                )
            )
        elif not (isinstance(reason, str) and reason.strip()):
            findings.append(
                Finding(
                    rule="config-waiver-unknown",
                    file=CONFIG_WAIVERS_PATH,
                    line=0,
                    message=f"waiver {key!r} has no reason",
                    remedy=(
                        "every waiver documents WHY the knob needs no "
                        "status rule — write the reason"
                    ),
                )
            )

    for cls, fields in classes.items():
        for fname, line in fields:
            if fname in guarded:
                continue
            if f"{cls}.{fname}" in waivers:
                continue
            findings.append(
                Finding(
                    rule="config-guard",
                    file=configs_rel,
                    line=line,
                    message=(
                        f"{cls}.{fname} is not reachable from any "
                        f"status.py rule — an illegal or typo'd value "
                        f"would be silently ignored (the anti-pattern "
                        f"every PR since 2 re-litigates)"
                    ),
                    remedy=(
                        f"add a status.py rule that validates "
                        f"{cls}.{fname} (rejecting illegal combinations "
                        f"with the remedy named), or waive it with a "
                        f"reason in {CONFIG_WAIVERS_PATH}"
                    ),
                )
            )
    return findings


# --------------------------------------------------------------------------- #
# rule: nullable-JSONL discipline
# --------------------------------------------------------------------------- #


def _schema_fields(events_tree: ast.Module) -> Dict[str, Tuple[bool, str]]:
    """Parse ``STEP_EVENT_FIELDS`` from events.py's AST: ``{field:
    (required, kind)}``."""
    for node in events_tree.body:
        if isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            if node.target.id != "STEP_EVENT_FIELDS":
                continue
            value = node.value
        elif isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "STEP_EVENT_FIELDS"
            for t in node.targets
        ):
            value = node.value
        else:
            continue
        out: Dict[str, Tuple[bool, str]] = {}
        if isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if not (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and isinstance(v, ast.Tuple)
                    and len(v.elts) == 2
                    and all(isinstance(e, ast.Constant) for e in v.elts)
                ):
                    continue
                out[k.value] = (bool(v.elts[0].value), str(v.elts[1].value))
        return out
    return {}


def _emitted_keys(tree: ast.Module) -> List[Tuple[str, int]]:
    """Namespaced string keys an ``event_fields``-family function can
    emit: literal dict keys and ``out["key"] = ...`` subscript stores."""
    keys: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in _JSONL_EMITTER_FNS
        ):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    for k in sub.keys:
                        if isinstance(k, ast.Constant) and isinstance(
                            k.value, str
                        ):
                            keys.append((k.value, k.lineno))
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if (
                            isinstance(t, ast.Subscript)
                            and isinstance(t.slice, ast.Constant)
                            and isinstance(t.slice.value, str)
                        ):
                            keys.append((t.slice.value, t.lineno))
    return [
        (k, ln)
        for k, ln in keys
        if any(k.startswith(p) for p in _JSONL_NAMESPACES)
    ]


def check_jsonl_schema(
    repo_root: str,
    emitters: Optional[Sequence[str]] = None,
    schema_path: Optional[str] = None,
) -> List[Finding]:
    """Conditionally-emitted JSONL keys must exist in the step-event
    schema with a NULLABLE, non-required kind: a key the schema does not
    know fails validation at emit time (or worse, silently passes when
    validation is off and breaks every reader), and a required kind
    contradicts 'the field is absent without the config'."""
    findings: List[Finding] = []
    schema_path = schema_path or os.path.join(repo_root, EVENTS_SCHEMA_PATH)
    schema = _schema_fields(_parse(schema_path))
    if not schema:
        return [
            Finding(
                rule="jsonl-schema",
                file=_rel(repo_root, schema_path),
                line=0,
                message=(
                    "STEP_EVENT_FIELDS not found as a literal dict — the "
                    "JSONL discipline cannot be checked"
                ),
                remedy=(
                    "keep STEP_EVENT_FIELDS a module-level literal dict "
                    "of field -> (required, kind)"
                ),
            )
        ]
    for rel in emitters if emitters is not None else JSONL_EMITTER_MODULES:
        path = os.path.join(repo_root, rel)
        if not os.path.exists(path):
            continue
        for key, line in _emitted_keys(_parse(path)):
            if key not in schema:
                findings.append(
                    Finding(
                        rule="jsonl-schema",
                        file=rel,
                        line=line,
                        message=(
                            f"event_fields emits {key!r} which is not in "
                            f"events.py STEP_EVENT_FIELDS — "
                            f"validate_step_event would reject every "
                            f"record carrying it"
                        ),
                        remedy=(
                            f"declare {key!r} in STEP_EVENT_FIELDS with "
                            f"a nullable kind (and document its "
                            f"semantics there — the schema is the "
                            f"single source of truth)"
                        ),
                    )
                )
                continue
            required, kind = schema[key]
            if required or not kind.startswith("nullable"):
                findings.append(
                    Finding(
                        rule="jsonl-schema",
                        file=rel,
                        line=line,
                        message=(
                            f"conditionally-emitted key {key!r} is "
                            f"declared {'required' if required else ''}"
                            f"{' ' if required else ''}kind={kind!r} in "
                            f"the schema — but subsystem fields are "
                            f"ABSENT without their config, so the "
                            f"schema must allow that"
                        ),
                        remedy=(
                            f"declare {key!r} optional with a "
                            f"nullable_* kind in STEP_EVENT_FIELDS"
                        ),
                    )
                )
    return findings


# --------------------------------------------------------------------------- #
# rule: banned APIs
# --------------------------------------------------------------------------- #


def check_banned_apis(
    repo_root: str,
    jax_free: Optional[Sequence[str]] = None,
    no_device_get: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Module-scope jax imports in the jax-free drivers, and
    ``device_get`` anywhere in the engine/serving hot paths."""
    findings: List[Finding] = []
    for rel in jax_free if jax_free is not None else JAX_FREE_MODULES:
        path = os.path.join(repo_root, rel)
        if not os.path.exists(path):
            continue
        tree = _parse(path)
        for node in _module_scope_walk(tree):
            mods: List[str] = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                mods = [node.module]
            for mod in mods:
                root = mod.split(".")[0]
                if root in ("jax", "jaxlib"):
                    findings.append(
                        Finding(
                            rule="banned-jax-import",
                            file=rel,
                            line=node.lineno,
                            message=(
                                f"module-scope import of {mod!r} in a "
                                f"jax-free module — a wedged TPU tunnel "
                                f"hangs this process at backend init "
                                f"(BENCH_NOTES incident log), and the "
                                f"supervisor/driver contract is that it "
                                f"never pays that risk"
                            ),
                            remedy=(
                                "move the import inside the function "
                                "that needs it, or run the jax-"
                                "dependent work in a subprocess "
                                "(the scripts/autotune.py discipline)"
                            ),
                        )
                    )
    for rel in (
        no_device_get if no_device_get is not None
        else DEVICE_GET_BANNED_MODULES
    ):
        path = os.path.join(repo_root, rel)
        if not os.path.exists(path):
            continue
        for node in ast.walk(_parse(path)):
            hit = (
                isinstance(node, ast.Attribute)
                and node.attr == "device_get"
            ) or (isinstance(node, ast.Name) and node.id == "device_get")
            if hit:
                findings.append(
                    Finding(
                        rule="banned-device-get",
                        file=rel,
                        line=node.lineno,
                        message=(
                            "device_get in an engine/serving hot path — "
                            "a synchronous per-dispatch host fetch "
                            "breaks the zero-extra-dispatch sentinel "
                            "discipline (PR 3) and serializes the "
                            "async dispatch pipeline"
                        ),
                        remedy=(
                            "compute the value INSIDE the compiled "
                            "program and fetch it with the sentinel "
                            "row / telemetry cadence; save paths use "
                            "io_ops' collective-safe gather instead"
                        ),
                    )
                )
    return findings


# --------------------------------------------------------------------------- #
# the full lint
# --------------------------------------------------------------------------- #


def run_invariant_lints(repo_root: str) -> List[Finding]:
    """Run every jax-free rule over the tree; [] on a clean tree (the
    merged-tree contract ``make lint`` enforces)."""
    findings: List[Finding] = []
    findings += check_wire_formats(repo_root)
    findings += check_config_coverage(repo_root)
    findings += check_jsonl_schema(repo_root)
    findings += check_banned_apis(repo_root)
    return findings

"""Static analysis over the repo's codified disciplines (ISSUE 15).

Two halves behind one CLI (``scripts/stoke_lint.py``) and one facade
hook (``Stoke.audit()``):

- :mod:`stoke_tpu.analysis.invariants` — the jax-free, AST-based
  invariant linter (append-only wire formats, config-knob status-rule
  coverage, nullable-JSONL discipline, banned APIs).  Loadable by FILE
  so the CLI never imports jax.
- :mod:`stoke_tpu.analysis.program` — the program auditor over lowered
  jaxpr/HLO step/serve programs (donation integrity, hidden host
  round-trips, recompile hazards, sharding/collective accounting).
- :mod:`stoke_tpu.analysis.hlo_text` — the ONE MLIR/HLO module-name
  normalizer shared by the AOT compile-cache key and the auditor.

See docs/analysis.md for the rule catalog and waiver format.
"""

from stoke_tpu.analysis.hlo_text import normalize_module_name
from stoke_tpu.analysis.invariants import (
    Finding,
    check_banned_apis,
    check_config_coverage,
    check_jsonl_schema,
    check_wire_formats,
    run_invariant_lints,
)
from stoke_tpu.analysis.program import (
    AuditReport,
    ProgramSpec,
    abstractify_args,
    audit_program_specs,
)

__all__ = [
    "AuditReport",
    "Finding",
    "ProgramSpec",
    "abstractify_args",
    "audit_program_specs",
    "check_banned_apis",
    "check_config_coverage",
    "check_jsonl_schema",
    "check_wire_formats",
    "normalize_module_name",
    "run_invariant_lints",
]

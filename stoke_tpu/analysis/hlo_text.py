"""Shared lowered-program text normalization (ISSUE 15 satellite).

One normalizer, two consumers: the AOT compile-cache key
(:func:`stoke_tpu.compile_cache.hlo_cache_key`) and the program auditor
(:mod:`stoke_tpu.analysis.program`) both reason about lowered program
text with the MLIR/HLO module NAME removed — the name carries the jit
wrapper's function name plus a per-process uniquifying counter
(``module @jit__fused.1`` when a second facade in the same process
lowers the identical program), and a renamed module is still the same
program.  Two hand-rolled normalizers would drift the moment one of
them learned a new header form, silently splitting the cache key from
the auditor's view of "the same program" — so the regexes live here and
nowhere else.

Deliberately jax-free (pure ``re``): the compile cache imports this in
jax contexts, but nothing here needs a backend.
"""

from __future__ import annotations

import re

#: MLIR module header name (``module @jit__fused attributes ...``) and
#: classic HLO header (``HloModule jit__fused, ...``) — the only places
#: the program's WRAPPER name appears in the lowered text.
#: ``Lowered.as_text()`` emits StableHLO MLIR on current jax, classic
#: ``HloModule`` headers on older ones — both forms normalized.
MLIR_MODULE_RE = re.compile(r"^(module @)[^\s{]+", flags=re.M)
HLO_MODULE_RE = re.compile(r"^(HloModule )[^\s,]+", flags=re.M)


def normalize_module_name(text: str) -> str:
    """Replace the module's wrapper-derived NAME with a fixed token so
    identical programs compare (and hash) equal regardless of which jit
    wrapper — or which process — lowered them.  Everything else,
    including the mhlo partition/replica attributes, is preserved."""
    body = MLIR_MODULE_RE.sub(r"\1m", text, count=1)
    return HLO_MODULE_RE.sub(r"\1m", body, count=1)

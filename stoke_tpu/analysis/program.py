"""Program auditor: static analysis over lowered jaxpr/HLO step programs
(ISSUE 15 tentpole, jax half).

The invariants every subsystem asserts per-PR with bespoke tests —
donation integrity, zero hidden host round-trips, bounded recompiles,
accounted collectives — become one pass over the LOWERED text of the
programs a live build actually dispatches.  The engine and serving
engine record one :class:`ProgramSpec` per (program, shape signature) at
their dispatch funnels (``StepEngine._aot_call`` /
``ServingEngine._dispatch``): the program name, the jitted callable, the
ABSTRACT argument tree (``jax.ShapeDtypeStruct`` per array leaf, shapes/
dtypes/shardings only — never live buffers, which the next step's
donation deletes), and the declared ``donate_argnums``.  Auditing lowers
each spec (``fn.lower`` — tracing only, no compile, no dispatch: the
``Stoke.audit()`` acceptance asserts dispatch-count equality) and walks
the normalized StableHLO/HLO text.

Checks (rule ids; every finding names the remedy):

- ``audit-donation`` — a program that DECLARES donated argnums whose
  lowered text carries no input/output aliasing annotation for them
  (``tf.aliasing_output`` / ``jax.buffer_donor``): the donation was
  silently lost, which means the in-place state update the engine's
  memory budget assumes is actually a copy.
- ``audit-deserialized`` — a dispatch callable that is NOT a plain
  ``jax.jit`` wrapper (no ``.lower``): the PR-6/PR-14 hazard class —
  deserialized executables lose donated-input bookkeeping, and chaining
  them over carried training state silently corrupts numerics
  (tests/test_compile_cache.py pins the evidence; a stale host
  reference read after its buffer was donated is the same class).
- ``audit-hidden-transfer`` — host callbacks (``pure_callback`` /
  ``io_callback`` / debug callbacks) or infeed/outfeed inside a step
  program: a host round-trip per dispatch, breaking the PR-3
  zero-extra-dispatch sentinel discipline.
- ``audit-weak-type`` — weak-typed or raw-Python-scalar argument
  leaves: a closure/argument leak that re-traces (and silently
  recompiles) whenever the surrounding dtype context changes.
- ``audit-recompile-churn`` — a program whose recorded shape-signature
  count exceeds the churn threshold (ragged batches / drifting pad
  lengths), or approaches the engine's 1024-entry memo cap, beyond
  which recompile detection and the AOT ledger disengage.
- ``audit-replicated-bytes`` — tensors annotated ``{replicated}`` above
  a byte threshold in a partitioned (``mhlo.num_partitions > 1``)
  program: each device holds a full copy of something the mesh was
  supposed to shard.
- ``audit-comm-bytes`` — cross-check against the gradient transport's
  analytic accounting: an active transport claiming bytes-on-wire whose
  apply-family program contains no explicit collective (the accounting
  drifted from the program), or manual collectives in an apply-family
  program with NO active transport (traffic nothing accounts —
  ``bytes_per_step`` would under-report the wire).
- ``audit-cost-drift`` (ISSUE 18) — serve-program analytic cost vs the
  committed ``analysis/manifests/program_costs.json`` manifest: each
  serve spec is re-lowered for its XLA cost analysis (FLOPs / bytes
  accessed — the same numbers the roofline observatory's cards carry)
  and compared against the pinned entry at matching shape signature.
  A relative deviation beyond the manifest tolerance fires IN BOTH
  directions (golden-file semantics: a silent bloat is a perf
  regression; a silent shrink means the pin is stale), so a refactor
  that quietly inflates a serve program fails CI on CPU with no
  hardware in the loop.  Unpinned serve programs fire too — a new
  program must be pinned when it lands.  Signature mismatches (the
  engine geometry changed) and backends without cost analysis are
  NOTES, not findings: geometry changes re-pin via ``scripts/
  stoke_lint.py --update-costs``.

Program findings use a ``<jit:NAME>`` pseudo-file and line 0 — the
"file" is the compiled program, not a source line.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from stoke_tpu.analysis.hlo_text import normalize_module_name
from stoke_tpu.analysis.invariants import Finding

#: step programs whose apply boundary runs the gradient transport — the
#: comm cross-check applies to these only (accum/fused_nb micro-steps
#: never exchange gradients; serve programs have no transport at all)
APPLY_FAMILY = ("apply", "fused", "window", "multi")

#: shape-signature count above which a program is churn-flagged (serve
#: prefill legitimately owns one signature per pad bucket, so the
#: default sits well above any bounded bucket ladder)
DEFAULT_CHURN_THRESHOLD = 32

#: replicated-tensor byte floor for the sharding audit (64 MiB — big
#: enough that real models' replicated biases/norms never trip it)
DEFAULT_REPLICATED_BYTES = 64 << 20

#: MLIR element-type byte widths (for tensor<...> byte accounting)
_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8E4M3FN": 1, "f8E5M2": 1,
    "i64": 8, "i32": 4, "i16": 2, "i8": 1, "i1": 1,
    "ui64": 8, "ui32": 4, "ui16": 2, "ui8": 1, "pred": 1,
}

_COLLECTIVE_RE = re.compile(
    r"stablehlo\.(all_reduce|all_gather|reduce_scatter|collective_permute|"
    r"all_to_all)\b|\b(all-reduce|all-gather|reduce-scatter|"
    r"collective-permute|all-to-all)\b"
)
_CALLBACK_RE = re.compile(r"custom_call\s+@([\w.]*callback[\w.]*)")
_INOUTFEED_RE = re.compile(r"stablehlo\.(infeed|outfeed)\b|\b(infeed|outfeed)\(")
_DONOR_ATTR_RE = re.compile(r"tf\.aliasing_output|jax\.buffer_donor")
_PARTITIONS_RE = re.compile(r"mhlo\.num_partitions = (\d+)")
_ARG_SPLIT_RE = re.compile(r"(?=%arg\d+: )")
_ARG_NUM_RE = re.compile(r"%arg(\d+): ")
#: a tensor type IMMEDIATELY followed by its attr dict (arg/result
#: annotations) — attr values may be quoted strings containing braces
#: (mhlo.sharding = "{replicated}"), hence the quote-aware body.
#: Single-char alternation branch: a ``[^{}"]+`` run inside the star
#: is ambiguous and backtracks exponentially on large program texts
_TENSOR_ATTRS_RE = re.compile(
    r'tensor<([^>]+)>\s\{((?:[^{}"]|"[^"]*")*)\}'
)
_SHARDING_RESULT_RE = re.compile(r"->\s*tensor<([^>]+)>")


@dataclass
class ProgramSpec:
    """One registered step/serve program, recorded at its dispatch
    funnel: everything the auditor needs to re-lower it without touching
    (or retaining) live buffers."""

    program: str
    fn: Any
    abstract_args: Tuple[Any, ...]
    donate_argnums: Tuple[int, ...] = ()
    #: descriptions of weak-typed / raw-Python-scalar arg leaves found at
    #: record time (the aval conversion would erase weakness, so it is
    #: detected before conversion)
    weak_leaves: Tuple[str, ...] = ()
    #: where the spec came from ("engine" / "serve") — display only
    source: str = "engine"


@dataclass
class AuditReport:
    """The program-audit result: per-program findings plus the audited
    program inventory (so "zero findings" is distinguishable from
    "nothing was audited")."""

    findings: List[Finding] = field(default_factory=list)
    programs: List[str] = field(default_factory=list)
    #: rules that could NOT run (e.g. churn without signature tracking)
    #: — a clean report must be distinguishable from an unchecked one
    notes: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def format(self) -> str:
        head = (
            f"program audit: {len(self.programs)} program(s), "
            f"{len(self.findings)} finding(s)"
        )
        lines = [head] + [f.format() for f in self.findings]
        lines += [f"note: {n}" for n in self.notes]
        return "\n".join(lines)


def abstractify_args(args: tuple) -> Tuple[tuple, Tuple[str, ...]]:
    """Live dispatch args → (abstract arg tree, weak-leaf descriptions).

    Array leaves become ``ShapeDtypeStruct`` (sharding preserved when it
    is a mesh placement — lowering under the run's real shardings keeps
    the audited text the dispatched program's); scalars and everything
    else pass through unchanged.  Weakness is recorded HERE because the
    aval conversion erases it: jax arrays flagged ``weak_type`` and raw
    Python ints/floats/complex both re-trace on dtype-context changes.
    """
    from jax.sharding import NamedSharding

    weak: List[str] = []
    flat, treedef = jax.tree_util.tree_flatten(args)
    out = []
    for i, leaf in enumerate(flat):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            if getattr(leaf, "weak_type", False):
                weak.append(
                    f"leaf {i}: weak-typed {leaf.dtype} array "
                    f"(a Python scalar promoted at trace time)"
                )
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding):
                out.append(
                    jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)
                )
            else:
                out.append(jax.ShapeDtypeStruct(leaf.shape, leaf.dtype))
        else:
            if isinstance(leaf, (int, float, complex)) and not isinstance(
                leaf, bool
            ):
                weak.append(
                    f"leaf {i}: raw Python {type(leaf).__name__} argument"
                )
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out), tuple(weak)


# --------------------------------------------------------------------------- #
# lowered-text helpers
# --------------------------------------------------------------------------- #


def _main_signature(text: str) -> str:
    """The argument list of ``func.func public @main(...)`` — extracted
    by paren balance so nested region block-args (whose ``%argN`` names
    restart) never alias into the mapping."""
    marker = "@main("
    start = text.find(marker)
    if start < 0:
        return ""
    i = start + len(marker) - 1  # at the opening paren
    depth = 0
    for j in range(i, len(text)):
        if text[j] == "(":
            depth += 1
        elif text[j] == ")":
            depth -= 1
            if depth == 0:
                return text[i : j + 1]
    return ""


def _tensor_bytes(content: str) -> Optional[int]:
    """``tensor<...>`` payload → bytes: the x-separated dims with the
    element type as the final segment (``1024x1024xf32``); None on
    dynamic dims or exotic element types (skipped, never guessed)."""
    parts = content.split("x")
    width = _DTYPE_BYTES.get(parts[-1])
    if width is None:
        return None
    n = 1
    for d in parts[:-1]:
        if not d.isdigit():
            return None  # dynamic dim: skip
        n *= int(d)
    return n * width


def _arg_leaf_ranges(abstract_args: tuple) -> List[Tuple[int, int]]:
    """Flat-leaf index range per positional argument — the map from a
    ``donate_argnums`` entry to the MLIR ``%argN`` positions it covers
    (valid only when jit kept every leaf; callers cross-check counts)."""
    ranges = []
    pos = 0
    for arg in abstract_args:
        n = len(jax.tree_util.tree_leaves(arg))
        ranges.append((pos, pos + n))
        pos += n
    return ranges


# --------------------------------------------------------------------------- #
# analytic program cost (ISSUE 18: the cost-drift gate's measurement leg)
# --------------------------------------------------------------------------- #

#: default relative FLOPs/bytes deviation above which audit-cost-drift
#: fires (the manifest's "tolerance" key overrides; XLA's CPU cost model
#: is deterministic for a fixed program, so the slack absorbs cross-
#: version cost-model drift, not noise)
DEFAULT_COST_TOLERANCE = 0.05


def cost_signature(abstract_args: tuple) -> str:
    """Stable digest of a spec's argument geometry (shapes + dtypes of
    every array leaf, order-preserving).  Pinned beside the manifest's
    analytic numbers so a cost comparison against a DIFFERENT engine
    geometry (resized batch, longer context) reads as "not comparable"
    instead of a false drift finding."""
    leaves = [
        (tuple(l.shape), str(l.dtype))
        for l in jax.tree_util.tree_leaves(abstract_args)
        if hasattr(l, "shape") and hasattr(l, "dtype")
    ]
    return hashlib.sha256(repr(leaves).encode()).hexdigest()[:16]


def spec_cost_entry(spec: ProgramSpec) -> Optional[Dict[str, Any]]:
    """One manifest entry for a serve spec: the XLA cost analysis of the
    re-lowered program (lowering only — no compile, no dispatch) plus
    the geometry signature.  None when the backend reports no cost
    analysis (the gate then notes itself unchecked, never guesses)."""
    from stoke_tpu.telemetry.attribution import cost_analysis_of

    if not hasattr(spec.fn, "lower"):
        return None
    cost = cost_analysis_of(spec.fn, *spec.abstract_args)
    if cost is None:
        return None
    flops = float(cost.get("flops", 0.0) or 0.0)
    if flops <= 0:
        return None
    nbytes = cost.get("bytes accessed")
    return {
        "sig": cost_signature(spec.abstract_args),
        "flops": flops,
        "bytes_accessed": float(nbytes) if nbytes else None,
    }


def _rel_dev(measured: float, pinned: float) -> float:
    return abs(measured - pinned) / max(abs(pinned), 1e-12)


# --------------------------------------------------------------------------- #
# per-program memory (ISSUE 19: the memory-drift gate's measurement leg)
# --------------------------------------------------------------------------- #

#: default relative temp/peak-bytes deviation above which
#: audit-memory-drift fires (the manifest's "tolerance" key overrides).
#: Looser than DEFAULT_COST_TOLERANCE on purpose: XLA's temp-buffer
#: allocation shifts across compiler versions far more than its analytic
#: FLOP count does — the gate exists to catch a refactor DOUBLING a
#: buffer, not a version bump nudging padding
DEFAULT_MEM_TOLERANCE = 0.25


def spec_memory_entry(spec: ProgramSpec) -> Optional[Dict[str, Any]]:
    """One memory-manifest entry for a serve spec: the compiled
    executable's ``memory_analysis`` temp/peak bytes plus the geometry
    signature.  Unlike :func:`spec_cost_entry` this REQUIRES a compile
    (``memory_analysis`` lives on the executable, not the lowering) — so
    the memory gate runs only where the cost gate's lowering-only
    contract does not apply (``stoke_lint.py --programs``'s throwaway
    engines, never ``Stoke.audit()``'s dispatch-count-pinned path unless
    a manifest is explicitly supplied).  None when the backend reports
    no memory analysis (the gate then notes itself unchecked)."""
    from stoke_tpu.telemetry.attribution import memory_analysis_stats

    if not hasattr(spec.fn, "lower"):
        return None
    stats = memory_analysis_stats(spec.fn, *spec.abstract_args)
    if stats is None:
        return None
    peak = float(stats.get("peak_bytes", 0.0) or 0.0)
    if peak <= 0:
        return None
    return {
        "sig": cost_signature(spec.abstract_args),
        "temp_bytes": float(stats.get("temp_bytes", 0.0) or 0.0),
        "peak_bytes": peak,
    }


def _audit_memory_drift(
    specs: Sequence[ProgramSpec],
    report: "AuditReport",
    mem_manifest: Dict[str, Any],
    tolerance: float,
) -> None:
    """The memory-drift gate: serve specs' re-compiled memory_analysis
    temp/peak bytes vs the committed manifest, both directions
    (golden-file semantics, the _audit_cost_drift pattern)."""
    pinned = mem_manifest.get("programs", {}) or {}
    seen = set()
    for spec in specs:
        if spec.source != "serve" or spec.program in seen:
            continue
        seen.add(spec.program)
        entry = spec_memory_entry(spec)
        if entry is None:
            report.notes.append(
                f"audit-memory-drift not checked for {spec.program!r}: "
                f"backend reports no XLA memory analysis"
            )
            continue
        pin = pinned.get(spec.program)
        if pin is None:
            report.findings.append(
                Finding(
                    rule="audit-memory-drift",
                    file=f"<jit:{spec.program}>",
                    line=0,
                    message=(
                        f"serve program {spec.program!r} "
                        f"({entry['peak_bytes']:.0f} peak bytes) has no "
                        f"pinned entry in the program-memory manifest — "
                        f"its HBM regressions would be invisible to CI"
                    ),
                    remedy=(
                        "pin it: scripts/stoke_lint.py --update-mem "
                        "rewrites analysis/manifests/program_memory.json "
                        "from the live engines"
                    ),
                )
            )
            continue
        if pin.get("sig") != entry["sig"]:
            report.notes.append(
                f"audit-memory-drift not checked for {spec.program!r}: "
                f"argument geometry changed (sig {entry['sig']} vs "
                f"pinned {pin.get('sig')}) — re-pin with "
                f"scripts/stoke_lint.py --update-mem"
            )
            continue
        for field_name, measured in (
            ("temp_bytes", entry["temp_bytes"]),
            ("peak_bytes", entry["peak_bytes"]),
        ):
            pinned_v = pin.get(field_name)
            if pinned_v is None or measured is None:
                continue
            dev = _rel_dev(measured, pinned_v)
            if dev <= tolerance:
                continue
            direction = "grew" if measured > pinned_v else "shrank"
            report.findings.append(
                Finding(
                    rule="audit-memory-drift",
                    file=f"<jit:{spec.program}>",
                    line=0,
                    message=(
                        f"serve program {spec.program!r} "
                        f"{field_name} {direction} {dev:.1%} vs the "
                        f"pinned manifest ({measured:.0f} vs "
                        f"{pinned_v:.0f}, tolerance {tolerance:.0%}) at "
                        f"UNCHANGED argument geometry — a refactor "
                        f"changed this program's HBM footprint per "
                        f"dispatch"
                    ),
                    remedy=(
                        "if the footprint change is intentional, re-pin "
                        "with scripts/stoke_lint.py --update-mem; "
                        "otherwise find the buffer the refactor "
                        "grew/dropped (compare memory_analysis against "
                        "the last good commit)"
                    ),
                )
            )


def _audit_cost_drift(
    specs: Sequence[ProgramSpec],
    report: "AuditReport",
    cost_manifest: Dict[str, Any],
    tolerance: float,
) -> None:
    """The cost-drift gate: serve specs' re-lowered analytic cost vs the
    committed manifest, both directions (golden-file semantics)."""
    pinned = cost_manifest.get("programs", {}) or {}
    seen = set()
    for spec in specs:
        if spec.source != "serve" or spec.program in seen:
            continue
        seen.add(spec.program)
        entry = spec_cost_entry(spec)
        if entry is None:
            report.notes.append(
                f"audit-cost-drift not checked for {spec.program!r}: "
                f"backend reports no XLA cost analysis"
            )
            continue
        pin = pinned.get(spec.program)
        if pin is None:
            report.findings.append(
                Finding(
                    rule="audit-cost-drift",
                    file=f"<jit:{spec.program}>",
                    line=0,
                    message=(
                        f"serve program {spec.program!r} "
                        f"({entry['flops']:.0f} analytic FLOPs) has no "
                        f"pinned entry in the program-cost manifest — "
                        f"its cost regressions would be invisible to CI"
                    ),
                    remedy=(
                        "pin it: scripts/stoke_lint.py --update-costs "
                        "rewrites analysis/manifests/program_costs.json "
                        "from the live engines"
                    ),
                )
            )
            continue
        if pin.get("sig") != entry["sig"]:
            report.notes.append(
                f"audit-cost-drift not checked for {spec.program!r}: "
                f"argument geometry changed (sig {entry['sig']} vs "
                f"pinned {pin.get('sig')}) — re-pin with "
                f"scripts/stoke_lint.py --update-costs"
            )
            continue
        for field_name, measured in (
            ("flops", entry["flops"]),
            ("bytes_accessed", entry["bytes_accessed"]),
        ):
            pinned_v = pin.get(field_name)
            if pinned_v is None or measured is None:
                continue
            dev = _rel_dev(measured, pinned_v)
            if dev <= tolerance:
                continue
            direction = "grew" if measured > pinned_v else "shrank"
            report.findings.append(
                Finding(
                    rule="audit-cost-drift",
                    file=f"<jit:{spec.program}>",
                    line=0,
                    message=(
                        f"serve program {spec.program!r} analytic "
                        f"{field_name} {direction} {dev:.1%} vs the "
                        f"pinned manifest ({measured:.0f} vs "
                        f"{pinned_v:.0f}, tolerance {tolerance:.0%}) at "
                        f"UNCHANGED argument geometry — a refactor "
                        f"changed what this program computes per "
                        f"dispatch"
                    ),
                    remedy=(
                        "if the cost change is intentional, re-pin with "
                        "scripts/stoke_lint.py --update-costs; otherwise "
                        "find the op the refactor added/dropped "
                        "(compare lowered HLO against the last good "
                        "commit)"
                    ),
                )
            )


# --------------------------------------------------------------------------- #
# the audit
# --------------------------------------------------------------------------- #


def _where(spec: ProgramSpec) -> str:
    return f"<jit:{spec.program}>"


def _audit_one(
    spec: ProgramSpec,
    findings: List[Finding],
    *,
    transport_active: bool,
    comm_bytes: Optional[Dict[str, Any]],
    replicated_bytes_threshold: int,
) -> None:
    if not hasattr(spec.fn, "lower"):
        findings.append(
            Finding(
                rule="audit-deserialized",
                file=_where(spec),
                line=0,
                message=(
                    f"program {spec.program!r} dispatches through a "
                    f"callable with no .lower — a deserialized/pre-"
                    f"compiled executable.  Deserialization loses "
                    f"donated-input bookkeeping: chaining such calls "
                    f"over carried training state reads stale host "
                    f"references after their buffers were donated and "
                    f"silently corrupts numerics (the PR-6/PR-14 hazard "
                    f"class, pinned in tests/test_compile_cache.py)"
                ),
                remedy=(
                    "dispatch step programs through plain jax.jit only; "
                    "serve warm starts from the persistent XLA cache "
                    "(CompileConfig) and keep serialized artifacts for "
                    "one-shot offline use"
                ),
            )
        )
        return

    # weak-typed inputs recompile when the dtype context shifts — checked
    # from record-time leaf descriptions (conversion to avals erases it)
    if spec.weak_leaves:
        findings.append(
            Finding(
                rule="audit-weak-type",
                file=_where(spec),
                line=0,
                message=(
                    f"program {spec.program!r} takes weak-typed / raw "
                    f"Python scalar arguments "
                    f"({'; '.join(spec.weak_leaves)}) — each dtype-"
                    f"context change re-traces and silently recompiles "
                    f"against the engine's shape-signature memo"
                ),
                remedy=(
                    "pass scalars as typed arrays "
                    "(jnp.asarray(v, dtype)) or bake them into the "
                    "program as closed-over constants"
                ),
            )
        )

    try:
        lowered = spec.fn.lower(*spec.abstract_args)
        text = normalize_module_name(lowered.as_text())
    except Exception as e:  # pragma: no cover - depends on runtime
        findings.append(
            Finding(
                rule="audit-lowering",
                file=_where(spec),
                line=0,
                message=(
                    f"program {spec.program!r} could not be re-lowered "
                    f"for audit ({e!r})"
                ),
                remedy=(
                    "audit with the run's real mesh/backend live (the "
                    "recorded abstract args carry its shardings)"
                ),
            )
        )
        return

    # --- donation integrity ---------------------------------------- #
    donated = [
        a
        for a in spec.donate_argnums
        if a < len(spec.abstract_args)
        and any(
            hasattr(l, "shape")
            for l in jax.tree_util.tree_leaves(spec.abstract_args[a])
        )
    ]
    if donated:
        sig = _main_signature(text)
        # split on "%argN: " boundaries so each segment carries one
        # argument's full attr dict — attr values nest braces
        # (mhlo.sharding = "{replicated}"), which defeats a flat regex
        sig_args = {}
        for part in _ARG_SPLIT_RE.split(sig):
            m = _ARG_NUM_RE.match(part)
            if m:
                sig_args[int(m.group(1))] = bool(
                    _DONOR_ATTR_RE.search(part)
                )
        ranges = _arg_leaf_ranges(spec.abstract_args)
        total_leaves = ranges[-1][1] if ranges else 0
        per_argnum_valid = len(sig_args) == total_leaves
        for a in donated:
            if per_argnum_valid:
                lo, hi = ranges[a]
                ok = any(sig_args.get(i, False) for i in range(lo, hi))
            else:
                # jit pruned/merged inputs: fall back to whole-program
                # donor presence (still catches fully-lost donation)
                ok = any(sig_args.values()) or bool(
                    _DONOR_ATTR_RE.search(sig)
                )
            if not ok:
                findings.append(
                    Finding(
                        rule="audit-donation",
                        file=_where(spec),
                        line=0,
                        message=(
                            f"program {spec.program!r} declares "
                            f"donate_argnums={spec.donate_argnums} but "
                            f"argument {a} carries no input/output "
                            f"aliasing annotation in the lowered "
                            f"program — the donation was silently "
                            f"dropped (no matching output shape), so "
                            f"the 'in-place' state update is actually "
                            f"a full copy"
                        ),
                        remedy=(
                            "return an output whose shape/dtype matches "
                            "every donated buffer (state threads "
                            "through), or stop declaring the argnum "
                            "donated — a silently-copied donation "
                            "double-books device memory"
                        ),
                    )
                )

    # --- hidden host round-trips ------------------------------------ #
    cb = _CALLBACK_RE.search(text)
    feed = _INOUTFEED_RE.search(text)
    if cb or feed:
        what = cb.group(1) if cb else (feed.group(1) or feed.group(2))
        findings.append(
            Finding(
                rule="audit-hidden-transfer",
                file=_where(spec),
                line=0,
                message=(
                    f"program {spec.program!r} embeds a host round-trip "
                    f"({what}) — every dispatch blocks on a host "
                    f"callback/transfer, breaking the zero-extra-"
                    f"dispatch sentinel discipline (PR 3) and "
                    f"serializing the async pipeline"
                ),
                remedy=(
                    "compute diagnostics INSIDE the compiled program "
                    "and fetch them with the sentinel row at the "
                    "telemetry cadence; move true host work outside "
                    "the step program"
                ),
            )
        )

    # --- sharding: big replicated tensors on a partitioned program -- #
    pm = _PARTITIONS_RE.search(text)
    n_partitions = int(pm.group(1)) if pm else 1
    if n_partitions > 1:
        # each candidate is matched to ITS OWN sharding annotation —
        # a per-line scan would attribute a small replicated arg's
        # annotation to every big SHARDED tensor sharing the (single-
        # line) @main signature and false-fire on real models
        repl_sizes = [
            _tensor_bytes(content)
            for content, attrs in _TENSOR_ATTRS_RE.findall(text)
            if '"{replicated}"' in attrs
        ]
        # sharding-constraint intermediates: the attr dict precedes the
        # type there (custom_call @Sharding(... ) {mhlo.sharding = ...}
        # : (tensor<...>) -> tensor<...>)
        for line in text.splitlines():
            if "@Sharding" in line and '"{replicated}"' in line:
                m = _SHARDING_RESULT_RE.search(line)
                if m:
                    repl_sizes.append(_tensor_bytes(m.group(1)))
        # one finding per distinct size: the same value annotated at its
        # arg AND result position is one replication, not two
        flagged = 0
        for nbytes in sorted(
            {b for b in repl_sizes if b is not None}, reverse=True
        ):
            if nbytes <= replicated_bytes_threshold:
                continue
            findings.append(
                Finding(
                    rule="audit-replicated-bytes",
                    file=_where(spec),
                    line=0,
                    message=(
                        f"program {spec.program!r} keeps a "
                        f"{nbytes / 2**20:.1f} MiB tensor "
                        f"replicated across {n_partitions} "
                        f"partitions (> {replicated_bytes_threshold / 2**20:.0f}"
                        f" MiB threshold) — every device holds "
                        f"a full copy"
                    ),
                    remedy=(
                        "give the value a sharded placement "
                        "(partition rules / tier shardings) or "
                        "raise the audit threshold if the "
                        "replication is intentional"
                    ),
                )
            )
            flagged += 1
            if flagged >= 4:  # bound the noise per program
                break

    # --- collectives vs the transport's analytic bytes --------------- #
    if spec.program in APPLY_FAMILY:
        has_collective = bool(_COLLECTIVE_RE.search(text))
        onwire = (comm_bytes or {}).get("onwire", 0) or 0
        if transport_active and onwire > 0 and not has_collective:
            findings.append(
                Finding(
                    rule="audit-comm-bytes",
                    file=_where(spec),
                    line=0,
                    message=(
                        f"the gradient transport accounts {onwire} "
                        f"bytes-on-wire per step but program "
                        f"{spec.program!r} contains no explicit "
                        f"collective — bytes_per_step has drifted from "
                        f"the compiled program"
                    ),
                    remedy=(
                        "re-derive GradTransport.bytes_per_step from "
                        "the schedule the program actually lowers "
                        "(parallel/collectives.py _wire_bytes), or fix "
                        "the transport wiring"
                    ),
                )
            )
        elif not transport_active and has_collective:
            findings.append(
                Finding(
                    rule="audit-comm-bytes",
                    file=_where(spec),
                    line=0,
                    message=(
                        f"program {spec.program!r} lowers explicit "
                        f"(manual/shard_map) collectives but no "
                        f"gradient transport is active — this traffic "
                        f"is invisible to the analytic bytes-on-wire "
                        f"accounting (comm_bytes_* telemetry would "
                        f"under-report the wire)"
                    ),
                    remedy=(
                        "route manual collectives through the "
                        "GradTransport layer (parallel/collectives.py) "
                        "so their bytes are accounted, or extend "
                        "bytes_per_step for the new exchange"
                    ),
                )
            )


def audit_program_specs(
    specs: Sequence[ProgramSpec],
    *,
    transport_active: bool = False,
    comm_bytes: Optional[Dict[str, Any]] = None,
    shape_sig_counts: Optional[Dict[str, int]] = None,
    churn_threshold: int = DEFAULT_CHURN_THRESHOLD,
    memo_cap: int = 1024,
    replicated_bytes_threshold: int = DEFAULT_REPLICATED_BYTES,
    cost_manifest: Optional[Dict[str, Any]] = None,
    cost_tolerance: Optional[float] = None,
    mem_manifest: Optional[Dict[str, Any]] = None,
    mem_tolerance: Optional[float] = None,
) -> AuditReport:
    """Audit every recorded program spec.  Lowering/tracing only — no
    compile, no dispatch (``Stoke.audit()`` asserts dispatch-count
    equality on top of this contract) — EXCEPT the opt-in memory-drift
    gate below, whose measurement requires a compile.

    ``cost_manifest`` (ISSUE 18) arms the cost-drift gate: the parsed
    ``analysis/manifests/program_costs.json`` dict, against which every
    serve spec's re-lowered analytic FLOPs/bytes are compared
    (``cost_tolerance`` overrides the manifest's own tolerance).

    ``mem_manifest`` (ISSUE 19) arms the memory-drift gate the same way
    with ``analysis/manifests/program_memory.json``: every serve spec is
    re-COMPILED (``memory_analysis`` lives on the executable — supplying
    this manifest opts out of the no-compile contract for those specs)
    and its temp/peak bytes compared both directions at matching
    geometry signature (``mem_tolerance`` overrides the manifest's
    own)."""
    report = AuditReport()
    for spec in specs:
        report.programs.append(spec.program)
        _audit_one(
            spec,
            report.findings,
            transport_active=transport_active,
            comm_bytes=comm_bytes,
            replicated_bytes_threshold=replicated_bytes_threshold,
        )
    # recompile hazards are per-PROGRAM, not per-spec: the signature
    # count is the engine's churn ledger.  None means the ledger never
    # ran (the engine only tracks signatures when a telemetry
    # CompileTracker is attached) — say so instead of reporting a
    # silently-unchecked rule as clean
    if shape_sig_counts is None:
        report.notes.append(
            "audit-recompile-churn not checked: shape-signature "
            "tracking is off (add a TelemetryConfig to enable it)"
        )
    for program, count in (shape_sig_counts or {}).items():
        if count >= memo_cap:
            findings_msg = (
                f"program {program!r} hit the {memo_cap}-entry shape-"
                f"signature memo cap — recompile detection and the AOT "
                f"ledger have DISENGAGED for it"
            )
        elif count > churn_threshold:
            findings_msg = (
                f"program {program!r} has compiled {count} distinct "
                f"input-shape signatures (churn threshold "
                f"{churn_threshold}) — each new signature is a silent "
                f"full XLA recompile"
            )
        else:
            continue
        report.findings.append(
            Finding(
                rule="audit-recompile-churn",
                file=f"<jit:{program}>",
                line=0,
                message=findings_msg,
                remedy=(
                    "bucket/pad inputs to a bounded shape ladder (the "
                    "serve prefill_pad_multiple discipline) so the "
                    "program count stays finite"
                ),
            )
        )
    # cost-drift gate (ISSUE 18): armed only when a manifest is supplied
    # — the rule applies to serve specs (step-program cost has no pinned
    # manifest yet), and an unsupplied manifest is a note, not silence
    if cost_manifest is not None:
        tol = (
            cost_tolerance
            if cost_tolerance is not None
            else float(
                cost_manifest.get("tolerance", DEFAULT_COST_TOLERANCE)
            )
        )
        _audit_cost_drift(specs, report, cost_manifest, tol)
    elif any(spec.source == "serve" for spec in specs):
        report.notes.append(
            "audit-cost-drift not checked: no program-cost manifest "
            "supplied (scripts/stoke_lint.py --programs passes the "
            "committed analysis/manifests/program_costs.json)"
        )
    # memory-drift gate (ISSUE 19): armed only when a manifest is
    # supplied — same serve-spec scope and note-not-silence discipline as
    # the cost gate, but the measurement compiles (see docstring)
    if mem_manifest is not None:
        tol = (
            mem_tolerance
            if mem_tolerance is not None
            else float(
                mem_manifest.get("tolerance", DEFAULT_MEM_TOLERANCE)
            )
        )
        _audit_memory_drift(specs, report, mem_manifest, tol)
    elif any(spec.source == "serve" for spec in specs):
        report.notes.append(
            "audit-memory-drift not checked: no program-memory manifest "
            "supplied (scripts/stoke_lint.py --programs passes the "
            "committed analysis/manifests/program_memory.json)"
        )
    return report

"""Configuration layer: typed config dataclasses + option enums.

TPU-native re-design of the reference config system (stoke/configs.py:1-770).
The reference surfaces every tunable of its five GPU backends (DDP, Horovod,
DeepSpeed, fairscale, Apex/AMP) as 16 attrs classes.  On TPU those backends
collapse into one SPMD engine (mesh + named shardings + XLA collectives), so
the config surface regroups by *concern* rather than by backend:

- runtime selection enums  (reference: stoke/status.py:31-45)
- precision policy         (reference: AMPConfig configs.py:44, ApexConfig :68,
                            DeepspeedFP16Config :283)
- gradient clipping        (reference: ClipGradConfig :100, ClipGradNormConfig :113)
- data parallelism / mesh  (reference: DDPConfig :131, HorovodConfig :726)
- sharding tiers           (reference: FairscaleOSSConfig :577,
                            FairscaleSDDPConfig :597, FairscaleFSDPConfig :634,
                            DeepspeedZeROConfig :409)
- multi-host rendezvous    (reference: BackendOptions configs.py:36-41 +
                            env:///MPI discovery, distributed.py:491-525)
- activation checkpointing (reference: DeepspeedActivationCheckpointingConfig :222)
- checkpoint IO            (reference: io_ops.py save/load knobs)
- profiling                (reference: DeepspeedFlopsConfig :252,
                            wall_clock_breakdown :540)

Everything here is pure data (stdlib dataclasses) with validation deferred to
`stoke_tpu.status.StokeStatus`, mirroring the reference's split between the
config layer (L1) and the status/validation layer (L3).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, TypedDict


# --------------------------------------------------------------------------- #
# Option enums (reference: stoke/status.py:31-45, stoke/configs.py:20-41)
# --------------------------------------------------------------------------- #


class DeviceOptions(Enum):
    """Compute device selector (reference `gpu: bool` flag, stoke/stoke.py:141).

    The reference toggles CPU vs CUDA; here the accelerator is TPU.  ``cpu``
    maps to the JAX CPU backend (also used for simulated-device testing via
    ``--xla_force_host_platform_device_count``).
    """

    cpu = "cpu"
    tpu = "tpu"


class DistributedOptions(Enum):
    """Distributed strategy selector (reference: status.py:31-38 with
    {ddp, deepspeed, horovod}).

    On TPU the three process-wrapper backends collapse into a single SPMD
    engine driven by a device mesh; ``dp`` is data parallelism over the mesh
    ``data`` axis with XLA-compiled collectives over ICI/DCN (SURVEY.md §2.9).
    """

    dp = "dp"


class PrecisionOptions(Enum):
    """Mixed-precision selector (reference FP16Options: status.py:40-45 with
    {apex_O1, apex_O2, amp, deepspeed}).

    - ``full``: fp32 params + fp32 compute (reference "full" passthrough).
    - ``bf16``: fp32 params, bfloat16 compute.  TPU-native mixed precision:
      bf16 has an fp32-range exponent so no loss scaler is required
      (replaces the entire GradScaler machinery, reference fp16.py:694-806).
    - ``fp16``: fp32 params, float16 compute with a functional dynamic loss
      scaler for exact-parity experiments (reference native AMP semantics,
      fp16.py:731-748).
    """

    full = "full"
    bf16 = "bf16"
    fp16 = "fp16"


class ShardingOptions(Enum):
    """Sharding-tier ladder (the ZeRO-1/2/3 ladder; reference extensions.py).

    Not user-facing as an enum in the reference (three booleans:
    ``fairscale_oss``, ``fairscale_sddp``, ``fairscale_fsdp``); surfaced here
    for table-driven validation.
    """

    none = "none"
    oss = "oss"  # optimizer-state sharding (ZeRO-1; reference extensions.py:81-141)
    sddp = "sddp"  # + gradient sharding (ZeRO-2; reference extensions.py:219-286)
    fsdp = "fsdp"  # + parameter sharding (ZeRO-3; reference extensions.py:289-376)


class ParamNormalize(Enum):
    """Divisors for pretty-printing parameter counts
    (reference: stoke/utils.py:30-36)."""

    BILLION = 1e9
    GIGA = 2**30
    KILO = 2**10
    MEGA = 2**20
    MILLION = 1e6
    THOUSAND = 1e3


class LossReduction(Enum):
    """Cross-replica loss reduction (reference Horovod ops Average/Sum/Adasum,
    configs.py:20-25; DDP divides summed loss by world size,
    distributed.py:619-646)."""

    mean = "mean"
    sum = "sum"


class CheckpointFormat(Enum):
    """Checkpoint layouts (reference: consolidated rank-0 torch.save in
    DDPIO/HorovodIO io_ops.py:551-703 vs sharded DeepSpeed engine checkpoints
    io_ops.py:389-544)."""

    consolidated = "consolidated"
    sharded = "sharded"


# --------------------------------------------------------------------------- #
# Precision
# --------------------------------------------------------------------------- #


@dataclass
class PrecisionConfig:
    """Precision policy + functional loss-scaler tunables.

    Replaces reference AMPConfig (configs.py:44-65: init_scale, growth_factor,
    backoff_factor, growth_interval, enabled) and the Apex/DeepSpeed scaler
    configs (configs.py:68-97, :283-306).  The scaler fields only apply when
    ``precision == fp16``; bf16 needs none (fp32-range exponent).

    Attributes:
        param_dtype: dtype of the master copy of parameters (always fp32 by
            default, matching AMP master-weight semantics).
        output_dtype: dtype model outputs are cast to after compute (fp32 to
            keep user-side loss math stable).
        init_scale: initial loss scale (reference AMPConfig.init_scale 2**16).
        growth_factor: scale multiplier after ``growth_interval`` consecutive
            finite steps (reference AMPConfig.growth_factor 2.0).
        backoff_factor: scale multiplier on overflow (reference 0.5).
        growth_interval: finite-step window before growth (reference 2000).
        min_scale: floor for the dynamic scale.
        num_losses: number of independent loss scalers (reference Apex
            ``num_losses`` / per-loss ``amp.scale_loss(..., loss_id)``,
            fp16.py:545-579, :656-691).  With ``num_losses > 1`` each leaf of
            the user's ``loss()`` return gets its own dynamic scale: the
            shared forward is differentiated once per loss (VJP seeded with
            that loss's scale — same backward count as the reference's
            ``retain_graph`` loop), gradients are unscaled into the
            accumulation buffer immediately, and per-loss overflow backs off
            only the offending loss's scale.  fp16 only.
    """

    param_dtype: str = "float32"
    output_dtype: str = "float32"
    init_scale: float = 2.0**16
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    min_scale: float = 1.0
    num_losses: int = 1


# --------------------------------------------------------------------------- #
# Gradient clipping (reference: configs.py:100-128)
# --------------------------------------------------------------------------- #


@dataclass
class ClipGradConfig:
    """Clip gradients element-wise by value (reference configs.py:100-110)."""

    clip_value: float = 1.0


@dataclass
class ClipGradNormConfig:
    """Clip gradients by global norm (reference configs.py:113-128).

    On TPU the global norm is computed on logically-global (sharded) gradient
    arrays inside the compiled step, so the special per-backend synced-norm
    implementations of the reference (fp16.py:222-235 OSS/FSDP variants)
    collapse into one code path.
    """

    max_norm: float = 1.0
    norm_type: float = 2.0


# --------------------------------------------------------------------------- #
# Data parallel / mesh / rendezvous
# --------------------------------------------------------------------------- #


@dataclass
class DataParallelConfig:
    """SPMD data-parallel engine knobs.

    Replaces reference DDPConfig (configs.py:131-189) and HorovodConfig
    (configs.py:726-751).  Buckets, `find_unused_parameters`,
    `gradient_as_bucket_view`, compression etc. have no TPU equivalent: XLA
    owns collective scheduling/fusion.  What survives:

    Attributes:
        axis_name: mesh axis gradients/batch are sharded over.
        sync_batch_stats: cross-replica BatchNorm statistics (reference
            SyncBatchNorm conversion, distributed.py:575-579, :1318-1371).
            With jit-GSPMD over a global batch this is automatic — stats are
            computed over the logically-global batch; the flag is kept so the
            eval/io paths know batch stats are already synchronized.
        loss_reduction: how per-device losses combine (reference
            distributed.py:619-646 sum/world_size; HorovodOps configs.py:20-25).
        convert_to_sync_batchnorm: kept for API parity with reference
            DDPConfig.convert_to_sync_batch_norm (configs.py:176).
    """

    axis_name: str = "data"
    sync_batch_stats: bool = True
    loss_reduction: LossReduction = LossReduction.mean
    convert_to_sync_batchnorm: bool = False
    # opt-in: also shard this batch dim over the mesh "seq" axis when one
    # exists (pre-shards inputs for sequence-parallel attention instead of
    # relying on GSPMD resharding at the shard_map boundary)
    shard_seq_dim: Optional[int] = None
    seq_axis_name: str = "seq"


@dataclass
class CommConfig:
    """Gradient-transport layer: quantized gradient synchronization with
    error feedback and bucketed flattening (ISSUE 2 tentpole), plus the
    ZeRO-parity sharded weight-update path under oss/sddp/fsdp (ISSUE 8:
    quantized reduce-scatter → shard-local optimizer step → param
    all-gather, with the error-feedback residual itself sharded).

    No reference equivalent (the reference's DDP gradient compression hooks
    were never surfaced; its gradients always sync fp32).  TPU-native
    motivation: the DP/ZeRO path syncs gradients through compiler-inserted
    collectives, so gradient bytes-on-wire are the scaling tax of every
    multi-chip config; EQuARX (arXiv:2506.17615) shows a quantized
    all-reduce inside XLA recovers most of that bandwidth at negligible
    quality cost, and it composes with cross-replica weight-update sharding
    (arXiv:2004.13336 — the ``oss`` tier here).

    The transport runs ONCE per optimizer step at the apply boundary (the
    accumulation window commits locally; micro-steps never quantize):
    gradient leaves are flattened into ``bucket_mb`` buckets so many small
    conv/BN grads ride one collective, each bucket is exchanged as
    reduce-scatter → per-chunk-scaled (stochastic-rounding) quantize →
    all-gather over the mesh data axis, and the per-leaf quantization
    residual is carried in engine state and re-injected next step
    (error feedback — preserves convergence, arXiv:1901.09847 lineage).

    Simulation-fidelity note: at the JAX level the pre-reduction partial
    gradients live inside GSPMD, so the reduce-scatter leg quantizes the
    logically-reduced value (one quantization error) where a compiler-level
    implementation (EQuARX) quantizes each partial; the wire format, byte
    counts, and error-feedback machinery are identical, and the error
    feedback absorbs either noise source.  ``dtype="fp32"`` is an exact
    pass-through (bit-identical to running without a CommConfig).

    Attributes:
        dtype: wire dtype of the gradient exchange — "fp32" (pass-through),
            "bf16" (2 bytes/elem, deterministic cast), or "int8"
            (1 byte/elem + one f32 scale per ``chunk_elems`` chunk,
            ~3.9x fewer bytes-on-wire than fp32).
        bucket_mb: flat-bucket size in MB of fp32 gradient payload; leaves
            are concatenated in tree order until a bucket fills (one
            collective per bucket instead of one per leaf).
        error_feedback: carry the per-leaf quantization residual in engine
            state and add it to the next step's gradients before quantizing
            (int8/bf16 only; structurally absent for fp32 pass-through).
        strategy: "rs_ag" (reduce-scatter then quantized all-gather — the
            weight-update-sharding-compatible schedule) or "all_reduce"
            (single quantize → sum exchange → dequantize).
        chunk_elems: elements sharing one f32 scale in int8 mode (scale
            overhead = 4/chunk_elems bytes/elem; 512 → ~0.8%).
        stochastic_rounding: unbiased stochastic rounding for int8
            (deterministic round-to-nearest when False — useful for tests).
        shard_updates: weight-update sharding for the quantized exchange
            (ISSUE 8, arXiv:2004.13336 + arXiv:2506.17615): the gradient
            leg becomes a quantized reduce-scatter ONLY — each replica
            dequantizes and optimizer-steps just its 1/N shard (the
            error-feedback residual is itself sharded, 1/N memory per
            replica) and the updated parameters all-gather back.  ``None``
            (default) resolves automatically: sharded under the
            sddp/fsdp tiers (whose sharded grad buffers the replicated
            transport cannot serve), replicated under none/oss (the PR 2
            path, unchanged).  ``True`` forces the sharded path (requires
            an oss/sddp/fsdp tier and ``strategy="rs_ag"``); ``False``
            forces the replicated path (illegal under sddp/fsdp).
            Irrelevant for the ``fp32`` pass-through.
    """

    dtype: str = "fp32"
    bucket_mb: float = 25.0
    error_feedback: bool = True
    strategy: str = "rs_ag"
    chunk_elems: int = 512
    stochastic_rounding: bool = True
    shard_updates: Optional[bool] = None


def comm_shard_updates(cfg: Optional["CommConfig"], tier: "ShardingOptions") -> bool:
    """Resolve ``CommConfig.shard_updates``'s auto default against the
    active sharding tier — the single source of truth shared by the status
    legality rules and the engine's transport factory.  ``True`` means the
    apply boundary runs the sharded weight-update path (quantized
    reduce-scatter → shard-local step → param all-gather); ``False`` the
    PR 2 replicated exchange.  Always ``False`` for an inactive transport
    (no config / fp32 pass-through)."""
    if cfg is None or cfg.dtype == "fp32":
        return False
    if cfg.shard_updates is not None:
        return bool(cfg.shard_updates)
    return tier in (ShardingOptions.sddp, ShardingOptions.fsdp)


#: wire dtypes the transport understands (validated by the status layer)
COMM_DTYPES: Tuple[str, ...] = ("fp32", "bf16", "int8")
#: collective schedules the transport understands
COMM_STRATEGIES: Tuple[str, ...] = ("rs_ag", "all_reduce")


@dataclass
class MeshConfig:
    """Logical device mesh specification.

    The reference has no mesh concept (process-per-GPU); this is the TPU-native
    replacement for its backend/process-group configuration (SURVEY.md §2.9).
    Axes beyond ``data`` (e.g. ``model``, ``seq``, ``expert``) are first-class
    so later tiers (tensor/sequence/expert parallel) are mesh re-labelings, not
    rewrites.

    Attributes:
        axes: ordered mesh axis names.
        shape: devices per axis; -1 infers from device count (like numpy
            reshape).  ``None`` → 1-D mesh over all devices on ``axes[0]``.
        devices: explicit device list override (tests / subsets).
        dcn_axes: axis names that cross slice boundaries (mapped onto DCN
            rather than ICI when running multi-slice).
    """

    axes: Tuple[str, ...] = ("data",)
    shape: Optional[Tuple[int, ...]] = None
    devices: Optional[Any] = None
    dcn_axes: Tuple[str, ...] = ()


@dataclass
class DistributedInitConfig:
    """Multi-host rendezvous via ``jax.distributed.initialize``.

    Replaces the reference's launcher-provided env rendezvous
    (RANK/WORLD_SIZE/MASTER_ADDR, configs.py:186 ``init_method="env://"``) and
    MPI discovery (distributed.py:491-525).  All fields ``None`` → JAX infers
    from the environment (TPU metadata / coordinator env vars), which is the
    common TPU path.
    """

    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    local_device_ids: Optional[Sequence[int]] = None
    initialization_timeout: int = 300
    auto_initialize: bool = True


# --------------------------------------------------------------------------- #
# Sharding tiers (the ZeRO ladder)
# --------------------------------------------------------------------------- #


@dataclass
class OSSConfig:
    """Optimizer-state sharding (ZeRO-1 equivalent).

    Reference: FairscaleOSSConfig (configs.py:577-594) wrapping fairscale OSS
    (extensions.py:81-141).  TPU-native: optimizer-state leaves get a
    NamedSharding over the data axis (weight-update sharding,
    arxiv 2004.13336); XLA inserts the all-gathers/reduce-scatters.

    Attributes:
        min_shard_size: leaves with fewer elements stay replicated (sharding
            tiny tensors costs more in collective latency than it saves).
    """

    min_shard_size: int = 2**10


@dataclass
class SDDPConfig:
    """Gradient + optimizer-state sharding (ZeRO-2 equivalent).

    Reference: FairscaleSDDPConfig (configs.py:597-631) wrapping
    ShardedDataParallel (extensions.py:219-286).  TPU-native: the gradient
    accumulation buffer is sharded like the optimizer state, so XLA lowers the
    gradient combine to reduce-scatter instead of all-reduce.

    ``reduce_buffer_size``/``auto_refresh_trainable`` from the reference have
    no XLA equivalent (compiler-managed).
    """

    min_shard_size: int = 2**10
    broadcast_buffers: bool = True  # parity field (configs.py:612); no-op in SPMD


@dataclass
class FSDPConfig:
    """Fully-sharded parameters (ZeRO-3 / FSDP equivalent).

    Reference: FairscaleFSDPConfig (configs.py:634-723) wrapping
    FullyShardedDataParallel (extensions.py:289-376).  TPU-native: parameter
    leaves get NamedShardings over the data axis; XLA schedules the
    all-gather-before-use / reduce-scatter-after-grad that FSDP hand-implements
    (``reshard_after_forward`` ≈ XLA rematerializing gathers, controlled here
    by pairing with activation checkpointing).

    Attributes:
        min_weight_size: parameters with fewer elements stay replicated
            (reference FSDP ``min_num_params`` style bucketing).
        shard_axis_preference: "largest" shards the largest divisible dim;
            "first" shards dim 0 when divisible.
        reshard_after_forward: parity flag (configs.py:660); on TPU XLA decides
            when to discard gathered params, so this only toggles a remat hint.
    """

    min_weight_size: int = 2**10
    shard_axis_preference: str = "largest"
    reshard_after_forward: bool = True


# --------------------------------------------------------------------------- #
# Activation checkpointing (reference: configs.py:222-248)
# --------------------------------------------------------------------------- #


@dataclass
class PartitionRulesConfig:
    """User-supplied parameter partition rules — the tensor-parallelism hook.

    No reference equivalent (SURVEY.md §2.8: the reference has no model
    parallelism of any kind); this is TPU-native upside.  Each rule is
    ``(path_regex, spec)`` where ``path_regex`` is matched (``re.search``)
    against the '/'-joined parameter path and ``spec`` is a tuple of mesh
    axis names / None per dimension (a PartitionSpec).  First matching rule
    wins; non-matching parameters fall back to the active tier's placement
    (so TP composes with dp/oss/sddp/fsdp).  Gradients and optimizer-state
    leaves inherit the same matching (optax state paths contain the
    parameter path).

    Example (Megatron-style 2-way TP on a ("data","model") mesh):

        PartitionRulesConfig(rules=(
            (r"qkv/kernel",    (None, None, "model", None)),
            (r"ff_in/kernel",  (None, "model")),
            (r"ff_out/kernel", ("model", None)),
        ))
    """

    rules: Tuple[Tuple[str, Tuple], ...] = ()


@dataclass
class OffloadOptimizerConfig:
    """Optimizer-state offload to host memory (ZeRO-offload equivalent).

    Reference: DeepspeedOffloadOptimizerConfig (configs.py:309-343) moves
    optimizer state to CPU/NVMe.  TPU-native: optimizer-state shardings get
    ``memory_kind="pinned_host"`` so XLA keeps the state in host RAM and
    streams it through HBM during the (bandwidth-bound) update — trading
    update speed for HBM headroom.  NVMe/aio tiers
    (DeepspeedAIOConfig, configs.py:192-219) have no TPU equivalent; host
    memory is the offload tier.

    Attributes:
        pin_memory: parity field (configs.py:330); host staging is always
            pinned on TPU runtimes.
        fallback_to_device: if the runtime lacks host-memory-kind support
            (e.g. the CPU simulator), warn and keep state on device instead
            of failing.
    """

    pin_memory: bool = True
    fallback_to_device: bool = True


@dataclass
class OffloadParamsConfig:
    """Parameter offload to host memory (ZeRO-3-offload equivalent).

    Reference: DeepspeedOffloadParamConfig (configs.py:346-372) moves the
    fsdp-sharded parameters to CPU between steps (legal only with ZeRO-3;
    the reference enforces stage 3, and so does the status layer here).
    TPU-native: the parameter shardings get ``memory_kind="pinned_host"`` so
    each chip's parameter shard lives in host RAM between steps and XLA
    streams it through HBM for the forward/backward — trading step time for
    HBM capacity (model sizes beyond HBM).  NVMe/aio tiers
    (DeepspeedAIOConfig, configs.py:192-219) have no TPU equivalent; host
    memory is the offload tier.

    Attributes:
        pin_memory: parity field (reference configs.py:366); host staging is
            always pinned on TPU runtimes.
        fallback_to_device: if the runtime lacks host-memory-kind support
            (e.g. the CPU simulator), warn and keep params on device instead
            of failing.
    """

    pin_memory: bool = True
    fallback_to_device: bool = True


@dataclass
class OffloadDiskConfig:
    """Optimizer-state offload to DISK (ZeRO-Infinity NVMe-offload
    equivalent).

    Reference: ``DeepspeedAIOConfig`` (configs.py:192-221) + offload device
    "nvme" (configs.py:309-372, wired at distributed.py:1026-1102) stream
    optimizer state between NVMe and GPU memory through libaio.  TPU-native:
    optimizer state is only touched at the accumulation boundary, so between
    optimizer steps it is spilled to disk-backed memory-mapped files and the
    device buffers freed (``stoke_tpu.offload.DiskOptimizerStore``); the OS
    page cache plays the role of the reference's pinned staging buffers.
    Trades HBM *and* host-RAM headroom for h2d/d2h + IO latency per boundary.

    Mutually exclusive with :class:`OffloadOptimizerConfig` (one offload
    tier per state, like the reference's single ``offload_optimizer``
    device choice).

    Attributes:
        path: spill directory (ideally on NVMe).  Default: a fresh
            per-process temporary directory.
    """

    path: Optional[str] = None


@dataclass
class ActivationCheckpointingConfig:
    """Rematerialization policy mapped onto ``jax.checkpoint``.

    Reference: DeepspeedActivationCheckpointingConfig (configs.py:222-248),
    config-passthrough only (distributed.py:965-983).  TPU-native this is a
    first-class transform: ``policy`` selects a ``jax.checkpoint_policies``
    member applied to the model step.

    Attributes:
        policy: one of {"nothing_saveable", "dots_saveable",
            "dots_with_no_batch_dims_saveable", "everything_saveable"}.
        prevent_cse: forwarded to ``jax.checkpoint``.
    """

    policy: str = "nothing_saveable"
    prevent_cse: bool = True


# --------------------------------------------------------------------------- #
# Checkpoint IO (reference: io_ops.py)
# --------------------------------------------------------------------------- #


@dataclass
class CheckpointConfig:
    """Unified checkpoint behavior.

    Reference splits IO across four mixins (BaseStokeIO/DDPIO/HorovodIO/
    DeepspeedIO, io_ops.py:20-746); here one checkpointer with a format switch:
    ``consolidated`` gathers to host and writes one file (reference rank-0
    torch.save, io_ops.py:551-623), ``sharded`` writes per-host shards with a
    metadata blob via orbax/tensorstore (reference DeepSpeed engine sharded
    save, io_ops.py:389-483).

    ``save_every_n_steps`` + ``auto_path`` enable periodic auto-saving from
    ``step()``/``train_step()``; with ``Stoke.maybe_resume()`` this is the
    failure-recovery story (checkpoint-restart) — the reference has no
    failure handling at all (SURVEY.md §5: "static world; crash = job
    death").

    ``save_rank`` picks which process writes the consolidated payload and
    the metadata (reference ``DDPIO._save_rank`` / OSS
    ``consolidate_state_dict(recipient_rank)``, io_ops.py:551-623) — useful
    when only one host mounts durable storage.  Taken modulo the process
    count, so a config written for a larger pod degrades safely.  Sharded
    saves always write from every process; ``save_rank`` then only selects
    the metadata writer.

    ``offload_staging`` (ISSUE 14, requires ``async_save`` and the
    consolidated format — status-validated): zero-stall periodic saves.
    Instead of completing a blocking device→host gather on the main thread
    before the background writer takes over, the save stages the state
    through ``stoke_tpu.offload.StagedSnapshot`` — one compiled-copy
    dispatch on the step path, async host transfers off it, at most two
    snapshots in flight (double buffering) — and every process writes its
    own ``<key>.staged.rank<N>.npz`` shard files against normalized global
    indices, which also makes the on-disk layout topology-free (loadable
    onto any mesh; the elastic-resume substrate).  The emergency
    preemption save keeps its carefully-sequenced synchronous gather.
    """

    format: CheckpointFormat = CheckpointFormat.consolidated
    max_to_keep: Optional[int] = None
    async_save: bool = False
    save_every_n_steps: Optional[int] = None
    auto_path: Optional[str] = None
    auto_name: str = "auto"
    save_rank: int = 0
    offload_staging: bool = False


# --------------------------------------------------------------------------- #
# Profiling / observability (reference: configs.py:252-279, :540)
# --------------------------------------------------------------------------- #


@dataclass
class TensorboardConfig:
    """TensorBoard metrics logging (reference DeepspeedTensorboardConfig,
    configs.py:392-405 — passthrough there, first-class here).

    When supplied, the facade logs loss metrics (EMA, step loss, loss scale,
    counters) every ``log_every_n_steps`` optimizer steps from process 0,
    and exposes ``Stoke.log_scalar`` for user metrics.  Device→host metric
    transfers happen only at the logging cadence, never per micro-batch.

    Attributes:
        output_path: event-file directory (reference output_path).
        job_name: subdirectory / run name (reference job_name).
        log_every_n_steps: optimizer-step cadence for automatic metrics.
    """

    output_path: str = "tensorboard"
    job_name: str = "stoke"
    log_every_n_steps: int = 10


@dataclass
class TelemetryConfig:
    """Unified telemetry pipeline (``stoke_tpu.telemetry``): metrics
    registry + structured step events + scrape-able exposition.

    Supplying this config turns on the whole observability stack for a run:
    facade phase timers, data-loader wait/starvation accounting, XLA
    compile/recompile tracking, HBM high-watermark gauges, and labeled
    xprof spans feed one registry, drained at ``log_every_n_steps`` into
    the enabled sinks.  No reference equivalent (the reference's metrics
    story was DeepSpeed tensorboard passthrough, configs.py:392-405).

    Attributes:
        output_dir: directory for all sink outputs (``steps.jsonl``,
            ``metrics.prom``, ``tb/``).
        run_name: label stamped into the Prometheus exposition.
        log_every_n_steps: optimizer-step cadence for step records.
        jsonl: write structured step events (one JSON line per window).
        jsonl_all_ranks: multi-host — every process writes its own
            ``steps.rank<N>.jsonl`` (default: rank 0 only, like all sinks).
        prometheus: write the atomic text-exposition scrape file.
        prometheus_all_ranks: multi-host — every process writes its own
            ``metrics.rank<N>.prom`` so each host's node exporter can
            scrape its local file (expositions carry ``host`` /
            ``process_index`` labels, so the aggregated series never
            collide — the fleet-skew view's Prometheus leg, ISSUE 5).
        tensorboard: mirror step events into a native TB event stream
            under ``output_dir/tb`` (independent of ``TensorboardConfig``,
            which keeps driving the legacy loss/scaler scalars).
        sample_device_time: bracket one dispatch per logging window with
            ``block_until_ready`` to sample true device step time (one
            host sync per window — off for maximally async loops).
        grad_norm: compute the global gradient-buffer norm at each record
            boundary (one extra device reduction per window).
        track_compiles: count XLA backend compiles / recompiles via
            ``jax.monitoring`` listeners.
        track_hbm: refresh HBM high-watermark gauges from
            ``device.memory_stats()`` at each record.
        xprof_annotations: label engine phases in xprof timelines via
            ``jax.profiler.TraceAnnotation`` (nearly free outside traces).
    """

    output_dir: str = "telemetry"
    run_name: str = "stoke"
    log_every_n_steps: int = 10
    jsonl: bool = True
    jsonl_all_ranks: bool = False
    prometheus: bool = True
    prometheus_all_ranks: bool = False
    tensorboard: bool = False
    sample_device_time: bool = True
    grad_norm: bool = False
    track_compiles: bool = True
    track_hbm: bool = True
    xprof_annotations: bool = True


@dataclass
class TraceConfig:
    """Always-on structured host tracing (ISSUE 10 tentpole): a bounded
    span ring, Perfetto export, per-request serve timelines, and a
    critical-path summary.

    No reference equivalent (the reference has no tracing story at all);
    the prior art here is ``xprof_span`` — a ``jax.profiler
    .TraceAnnotation`` visible only inside an active xprof capture.  With
    this config, every annotated section (engine ``stoke/accum`` /
    ``stoke/dispatch`` / ``stoke/step``, facade ``stoke/place`` /
    ``stoke/io`` and the ``facade/*`` phase timers, loader waits,
    checkpoint save/wait, and the serving path's per-request
    admission → prefill → decode → evict spans) ALSO lands in a host-side
    ring of ``(name, track, t_start, dur, step, request_id, parent_id)``
    spans recorded from ``perf_counter`` pairs — no profiler attachment
    required, O(1) per span, no IO on the hot path.

    Default OFF — without this config no recorder is registered, the
    composed span helper degrades to the bare annotation, and the step
    programs/dispatch counts are bit-identical to a config-less run
    (tracing is purely host-side, so they are bit-identical WITH it too;
    tests pin both).

    Outputs: ``trace.rank<N>.json`` (chrome-trace/Perfetto JSON, one per
    process — ``scripts/merge_rank_traces.py`` aligns ranks by step
    anchor), ``Stoke.trace_summary`` (per-name self-time critical path),
    ``trace/*`` registry counters in the telemetry exposition, and a
    ``trace.json`` span ring in every flight-recorder post-mortem bundle.

    Attributes:
        output_dir: directory ``trace.rank<N>.json`` is exported into
            (every rank writes its own file; status-validated writable).
        ring_size: span-ring capacity (entries, FIFO; a full ring evicts
            oldest-first and counts ``trace/dropped_total``).
        export_on_close: write the trace file in ``close_telemetry()``
            (off for runs that only want the live summary/bundle ring).
    """

    output_dir: str = "trace"
    ring_size: int = 4096
    export_on_close: bool = True


#: actions a health detector may take when it fires (validated by status.py)
HEALTH_ACTIONS: Tuple[str, ...] = ("record", "warn", "dump", "halt")


@dataclass
class HealthConfig:
    """Training health monitor (ISSUE 3 tentpole): on-device numerics
    sentinels, host-side anomaly detectors, a crash flight recorder, and a
    hang watchdog.

    No reference equivalent (the reference's failure story is "crash = job
    death", SURVEY.md §5).  At pod scale silent numerics faults and hangs
    are first-order failures (arXiv:1909.09756), and the lossy int8
    gradient transport (ISSUE 2, EQuARX lineage arXiv:2506.17615) makes a
    standing error-feedback-divergence monitor a correctness requirement.
    Four pieces:

    1. **Sentinels** (``sentinels=True``): the compiled step additionally
       returns a tiny packed vector of per-step diagnostics (loss, global
       grad/param norms, update ratio, nonfinite-leaf count, scaler-skip
       flag, comm residual norm) computed *inside* the existing jit — zero
       extra device dispatches (this subsumes the host-side
       ``TelemetryConfig.grad_norm`` extra reduction).
    2. **Detectors**: host-side anomaly checks over the sentinel stream +
       registry counters, each with a configurable action — ``record``
       (count only), ``warn`` (count + warning), ``dump`` (count + write a
       post-mortem bundle), ``halt`` (dump + raise
       :class:`~stoke_tpu.telemetry.health.HealthHaltError` at the facade
       boundary).
    3. **Flight recorder**: a bounded ring of recent step events /
       sentinel rows / anomalies; dumped as a post-mortem bundle directory
       on anomaly ``dump``, uncaught step-path exception, SIGTERM/SIGUSR1,
       or watchdog trip (see docs/observability.md "Training health &
       post-mortems" for the bundle layout).
    4. **Watchdog** (``watchdog=True``): a daemon thread armed per
       dispatch that fires when no step completes within
       ``watchdog_timeout_s`` (the wedged-collective / dead-tunnel case),
       dumping all-thread stacks + the bundle and — with
       ``watchdog_kill=True`` — exiting with a distinct code the
       ``scripts/_supervise.py`` runner recognizes.

    Attributes:
        sentinels: compile the on-device diagnostics vector into every
            step path (requires a ``TelemetryConfig``; status-validated).
        ring_size: flight-recorder ring capacity (entries, FIFO).
        bundle_dir: post-mortem bundle directory (default:
            ``<TelemetryConfig.output_dir>/postmortem``).
        detector_warmup_steps: steps before the spike detectors may fire
            (their running mean/variance needs samples first).
        ema_alpha: EMA weight of the detectors' running mean/variance.
        loss_spike_zscore / loss_spike_action: fire when the step loss is
            more than this many running standard deviations above its EMA.
        grad_spike_zscore / grad_spike_action: same for the global grad
            norm.
        nonfinite_action: fire when any gradient leaf contains a
            non-finite value.  ``halt`` is illegal under fp16 (the dynamic
            scaler's skip handling already tolerates transient infs;
            status-validated).
        scaler_skip_streak / scaler_skip_action: fire after this many
            CONSECUTIVE fp16 scaler-skipped steps (scale collapse).
        recompile_storm_threshold / recompile_storm_window /
        recompile_storm_action: fire when the structural recompile counter
            (shape-signature collector) grows by >= threshold within the
            window (steps).
        starvation_streak / starvation_action: fire after this many
            consecutive steps with loader starvation time accrued.
        comm_residual_factor / comm_residual_action: fire when the
            error-feedback residual norm exceeds factor x its own EMA
            (quantization error outrunning re-injection) or goes
            non-finite.
        max_dumps: per-run cap applied separately to anomaly-triggered
            and exception-triggered bundle dumps (signal/watchdog/manual
            dumps are uncapped).
        dump_on_exception: write a bundle when the facade step path dies
            on an uncaught exception.
        dump_signals: install SIGTERM/SIGUSR1 handlers that dump a bundle
            (chained to any previous handler; main thread only).
        watchdog / watchdog_timeout_s: arm a per-dispatch hang watchdog;
            the timeout must be > 0 (status-validated).  The armed deadline
            scales with the optimizer steps one dispatch covers (a
            ``train_steps(n)`` segment gets ``n × timeout``), so
            multi-step scans are not false-tripped.
        watchdog_compile_grace_s: extra allowance added to the deadline
            until the FIRST optimizer step completes — covering warm-up
            XLA compilation, which can legitimately exceed the steady-state
            step timeout.  Mid-run recompiles (new shapes) get no grace;
            keep the timeout comfortably above your worst compile or pad
            this.
        watchdog_kill: after dumping, hard-exit the process with
            ``WATCHDOG_EXIT_CODE`` (``stoke_tpu.telemetry.health``) so a
            supervisor can distinguish "hung and self-terminated" from a
            generic timeout.
    """

    sentinels: bool = True
    ring_size: int = 256
    bundle_dir: Optional[str] = None
    detector_warmup_steps: int = 20
    ema_alpha: float = 0.02
    loss_spike_zscore: float = 6.0
    loss_spike_action: str = "warn"
    grad_spike_zscore: float = 6.0
    grad_spike_action: str = "warn"
    nonfinite_action: str = "dump"
    scaler_skip_streak: int = 8
    scaler_skip_action: str = "warn"
    recompile_storm_threshold: int = 3
    recompile_storm_window: int = 20
    recompile_storm_action: str = "warn"
    starvation_streak: int = 5
    starvation_action: str = "record"
    comm_residual_factor: float = 10.0
    comm_residual_action: str = "warn"
    max_dumps: int = 3
    dump_on_exception: bool = True
    dump_signals: bool = True
    watchdog: bool = False
    watchdog_timeout_s: float = 300.0
    watchdog_compile_grace_s: float = 600.0
    watchdog_kill: bool = False


@dataclass
class AttributionConfig:
    """Step-time attribution & goodput accounting (ISSUE 4 tentpole):
    per-program cost cards, live MFU/roofline gauges, a goodput ledger,
    and anomaly-triggered xprof capture.

    Requires a :class:`TelemetryConfig` (the attribution values surface
    through the JSONL step events and Prometheus exposition;
    status-validated).  Default OFF — without this config the step paths
    and compiled programs are untouched.  With it on, the engine runs
    ONE XLA ``cost_analysis`` per compiled step program signature
    (cached :class:`~stoke_tpu.telemetry.attribution.CostCard`) and the
    telemetry record gains ``achieved_tflops`` / ``mfu`` /
    ``hbm_bw_util`` / ``bound`` / ``goodput_*_s`` fields per window
    (MLPerf-scale TPU practice: per-step utilization and goodput are the
    primary scaling lens, arXiv:1909.09756).

    Attributes:
        peak_tflops: the chip's peak TFLOP/s for the active compute
            dtype — MFU's denominator.  Must be > 0 (status-validated);
            measure it with ``scripts/flops_probe.py``'s matmul-peak
            probe or use the datasheet number (v5e bf16 dense: 197).
        peak_hbm_gbps: HBM bandwidth peak (GB/s) for the
            memory-roofline bound and the ``hbm_bw_util`` gauge; 0
            disables the memory leg (compute-only roofline).
        ici_gbps: per-device interconnect bandwidth (GB/s) used to
            convert the gradient transport's analytic bytes-on-wire
            (ISSUE 2) into an estimated comm time for the bound
            classification; 0 disables the comm leg.
        ema_alpha: EMA weight of the step-wall-time running stats the
            capture z-score trigger uses.
        auto_capture: arm the anomaly-triggered profiler capture.
            Requires ``ProfilerConfig.trace_dir`` (status-validated):
            captured xprof trace windows land under it as
            ``auto-capture-<n>-step<k>-<reason>/``.
        capture_mfu_below: trigger a capture when the window MFU drops
            below this fraction (0 disables the MFU trigger).
        capture_step_zscore: trigger when the window wall time is more
            than this many running standard deviations above its EMA
            (0 disables the z-score trigger).
        capture_warmup_windows: windows before either trigger may fire
            (the running stats need samples; warm-up compiles would
            otherwise trip the z-score immediately).
        capture_steps: optimizer steps one capture window covers before
            the trace is stopped.
        max_captures: per-run cap on captures (a permanently-degraded
            run must not fill the disk with traces).
        capture_action: health-detector action the capture surfaces as
            when a ``HealthConfig`` is present (``record``/``warn``/
            ``dump``; validated against HEALTH_ACTIONS).
    """

    peak_tflops: float = 0.0
    peak_hbm_gbps: float = 0.0
    ici_gbps: float = 0.0
    ema_alpha: float = 0.1
    auto_capture: bool = False
    capture_mfu_below: float = 0.0
    capture_step_zscore: float = 4.0
    capture_warmup_windows: int = 5
    capture_steps: int = 2
    max_captures: int = 3
    capture_action: str = "record"


#: straggler-detector actions FleetConfig accepts (validated by status.py;
#: "halt" is deliberately excluded — a slow host is a performance
#: diagnosis, never a reason to kill the run)
FLEET_ACTIONS: Tuple[str, ...] = ("record", "warn", "dump")


@dataclass
class FleetConfig:
    """Fleet observability (ISSUE 5 tentpole): cross-host skew
    aggregation, straggler detection, and barrier-wait attribution.

    Requires a :class:`TelemetryConfig` (the fleet view surfaces through
    the JSONL step events and Prometheus exposition; status-validated).
    Default OFF — without this config the step paths, compiled programs,
    and telemetry records are untouched, and a single-process run with it
    on performs no collective at all (a fleet of one).

    With it on, every ``window_steps`` optimizer steps each host packs a
    small fixed-layout vector of window-local signals (step wall time,
    dispatch count, loader wait, starvation, compile time, barrier wait,
    goodput buckets, health-anomaly count, comm bytes —
    ``stoke_tpu.telemetry.fleet.FLEET_SIGNALS``) and ONE tiny in-band
    ``process_allgather`` (piggybacked on the telemetry record cadence;
    zero extra dispatches on the compiled step path) gives every host the
    full per-host matrix.  From it the run derives min/median/max/p99 +
    argmax-host per signal (``fleet/*`` Prometheus gauges), per-host
    step-time skew vs the fleet median, a loader-vs-compute skew
    classification, and barrier-wait attribution (wait charged to the
    straggler that arrived last, not the waiters) — emitted into the
    JSONL step events (``fleet/*`` fields), the end-of-run
    ``Stoke.fleet_summary``, and flight-recorder bundles (per-host matrix
    + straggler verdict at time of death).  MLPerf-scale motivation:
    per-host input and step-time skew dominate lost pod scaling
    (arXiv:1909.09756).

    Like every cross-host collective, the exchange assumes all hosts
    keep stepping: if one rank stops (a rank-local ``halt``-action
    health detector, a crash without process teardown) the others block
    in the next exchange until the runtime notices — on pods, pair with
    ``HealthConfig(watchdog=True)`` so a wedged exchange trips the hang
    watchdog instead of hanging silently.

    Attributes:
        window_steps: optimizer steps per fleet exchange window (>= 1;
            the exchange fires at the first telemetry record crossing
            each boundary, so the effective cadence is
            ``max(window_steps, TelemetryConfig.log_every_n_steps)``).
            The very first record only anchors the cadence and is
            discarded — its wall covers init-to-now warm-up compiles,
            whose per-host skew would pollute the first verdict — so the
            first exchange happens at the second boundary crossing.
        straggler_zscore: leave-one-out z-score of a host's lag
            (step-time skew + loader skew + barrier lateness) against
            the rest of the fleet above which the host is flagged
            (> 0; live on fleets of >= 3 hosts — with 2 hosts only the
            relative threshold below applies.  Leave-one-out because an
            all-host z-score is bounded by sqrt(n_hosts - 1) and a
            3-sigma threshold could never fire on small fleets).
        straggler_rel_frac: lag as a fraction of the fleet-median window
            wall time above which the host is flagged (> 0; fleet-size
            independent).
        straggler_windows: consecutive flagged windows on the SAME host
            before the ``fleet_straggler`` detector fires (>= 1; fires
            once per streak, then re-arms).
        straggler_action: what a firing does — ``record`` (count only),
            ``warn`` (count + warning), ``dump`` (count + post-mortem
            bundle; requires a ``HealthConfig`` whose recorder writes
            it, otherwise degrades to warn).  Validated against
            ``FLEET_ACTIONS``.
        rebalance: skew-reactive input rebalancing (ISSUE 14 tentpole c;
            default OFF — off keeps the step programs, loader behavior,
            and JSONL schema byte-identical, zero new fields).  When a
            straggler streak completes (the SAME K-window hysteresis that
            fires the ``fleet_straggler`` detector) with skew class
            ``loader``, the fleet shifts ``rebalance_rows`` samples of
            per-slice READ work from the flagged host to the host with the
            least loader wait.  The global batch, per-epoch sample set,
            and every host's device feed are unchanged — only which host
            reads (and decodes) which rows moves; the surplus rows ride
            one host-side allgather back to their canonical host.
            Requires loaders built from ``Stoke.DataLoader`` with a
            sampler exposing ``global_batches()``
            (``BucketedDistributedSampler``).  Surfaced as
            ``fleet/rebalance_*`` gauges and JSONL fields.
        rebalance_rows: samples moved per actuation (>= 1; the bounded
            step size).
        rebalance_max_frac: ceiling on any host's share deviation from
            the equal split, as a fraction of the per-host batch
            (0 < f < 1) — a persistently slow host sheds at most this
            much of its read work, never all of it.
    """

    window_steps: int = 10
    straggler_zscore: float = 3.0
    straggler_rel_frac: float = 0.25
    straggler_windows: int = 3
    straggler_action: str = "warn"
    rebalance: bool = False
    rebalance_rows: int = 1
    rebalance_max_frac: float = 0.25


@dataclass
class NumericsConfig:
    """Per-layer numerics observatory (ISSUE 12 tentpole): module
    sentinels, NaN provenance, and quantization-error attribution.

    Requires a :class:`TelemetryConfig` (the per-layer view surfaces
    through the JSONL step events and Prometheus exposition;
    status-validated).  Default OFF — without this config the compiled
    step programs are bit-identical, no ``numerics/*`` JSONL field or
    registry gauge exists, and the step paths are untouched.

    With it on, the compiled apply additionally returns one fixed-layout
    ``[n_groups, n_stats]`` f32 matrix of per-top-level-module raw sums
    (grad sum-of-squares / absmax / nonfinite-element count, param and
    update sum-of-squares — ``stoke_tpu.telemetry.numerics
    .NUMERICS_STATS``, a wire format) computed *inside* the existing
    step program — the PR-3 sentinel discipline: zero extra device
    dispatches, the matrix is fetched with the existing sentinel row.
    Host-side, the :class:`~stoke_tpu.telemetry.numerics
    .NumericsMonitor` derives per-group rms views (which recombine
    exactly to the global grad-norm sentinel), first-offending-layer
    NaN/Inf provenance (a ``numerics_provenance`` health detector when a
    ``HealthConfig`` is present), per-layer wire error for the PR-8
    sharded transport (per-bucket error-feedback residual norms mapped
    back to module groups), and per-layer dequant error for PR-9
    int8-served weights.  Outputs: ``numerics/*`` registry gauges, a
    nullable per-group JSONL block, ``Stoke.numerics_summary``,
    ``numerics.json`` in flight-recorder bundles, and the offline
    ``scripts/numerics_diff.py`` run-vs-run drift table.

    Attributes:
        grad_stats: compile the per-group stats matrix into every step
            path (the tentpole signal; False leaves the compiled
            programs untouched and keeps only the host-side
            quantization-error attribution).
        provenance_action: health-detector action when a non-finite
            value is first attributed to a layer — ``record`` / ``warn``
            / ``dump`` / ``halt`` (validated against ``HEALTH_ACTIONS``;
            ``halt`` is illegal under fp16, whose scaler tolerates
            transient infs by skipping the step).  Without a
            ``HealthConfig`` the action degrades to a bounded warning.
        wire_error: at the telemetry cadence, fetch the gradient
            transport's error-feedback residual norms and attribute them
            to module groups (one tiny host fetch per logged window; a
            no-op without a ``CommConfig`` carrying error feedback).
        per_group_jsonl: emit the per-group block into the JSONL step
            events (the ``numerics_diff.py`` input; scalar provenance /
            quant-error fields ride regardless).
        top_k: groups ranked in ``Stoke.numerics_summary`` (>= 1;
            status-validated).
    """

    grad_stats: bool = True
    provenance_action: str = "warn"
    wire_error: bool = True
    per_group_jsonl: bool = True
    top_k: int = 5


@dataclass
class MemoryConfig:
    """HBM capacity observatory (ISSUE 19 tentpole): per-subsystem
    memory ledger, OOM pre-flight, and per-program peak capture.

    Requires a :class:`TelemetryConfig` (the ledger surfaces through the
    JSONL step events and Prometheus exposition; status-validated).
    Default OFF — without this config no observatory is constructed, no
    ``mem/*`` JSONL field or registry gauge exists, and the compiled
    step/serve programs are HLO bit-identical (lowering-asserted).

    With it on, the facade (and :meth:`Stoke.serve`'s engine) computes
    an **analytic per-device resident ledger** from shape/dtype/sharding
    trees alone — params, optimizer state, grad-transport buckets +
    error-feedback residual (per-shard, so the PR-8 sharded transport
    ledgers 1/world of what the PR-2 replicated one does), the serving
    KV block pool, staged-snapshot buffers — whose components recombine
    EXACTLY into the reported resident total.  Per-program
    ``memory_analysis()`` peaks (argument/output/temp/generated-code
    bytes) are captured at both dispatch funnels through the PR-18
    cost-card machinery; an **OOM pre-flight** at ``build()``/``serve()``
    compares predicted peak (resident + max program temp) against device
    capacity and warns BEFORE the first dispatch with the largest
    contributors and remedies named.  Outputs: ``mem/*`` gauges + JSONL
    block, ``serve/mem_headroom_bytes``, ``Stoke.memory_summary``, and
    the committed ``analysis/manifests/program_memory.json`` drift gate
    (``stoke_lint.py --programs --mem-manifest``).

    Attributes:
        oom_margin_frac: pre-flight alarm threshold — warn when the
            predicted peak exceeds this fraction of device capacity
            (0 < frac <= 1; status-validated).
        capacity_bytes: device HBM capacity override for planning runs
            and capacity-blind backends (the CPU simulator reports no
            ``memory_stats``); None reads the live ``bytes_limit``
            (> 0 when set; status-validated).
        program_peaks: run one ``memory_analysis`` compile per distinct
            program signature at the dispatch funnels (the temp-peak leg
            of the pre-flight and the drift-gate pins; False keeps the
            ledger analytic-only).
        preflight: run the OOM pre-flight at ``build()``/``serve()``
            (False keeps the ledger and gauges but never warns).
    """

    oom_margin_frac: float = 0.9
    capacity_bytes: Optional[int] = None
    program_peaks: bool = True
    preflight: bool = True


@dataclass
class OpsPlaneConfig:
    """Live ops plane (ISSUE 20 tentpole): a stdlib-only, read-only HTTP
    observatory every rank can expose while it runs — ``/metrics``
    (Prometheus exposition, the SAME renderer the file sink uses),
    ``/healthz`` (200 ↔ 503 drain signal from the health monitor),
    ``/statusz`` (pinned JSON: goodput + memory + trace + serving
    summaries), ``/requests`` (in-flight serve table with SLO deadline
    headroom), ``/trace`` (Perfetto span-ring snapshot), and
    ``/profile?seconds=N`` (bounded on-demand xprof capture riding the
    ``AttributionConfig.max_captures`` budget).

    Requires a :class:`TelemetryConfig` (the plane serves the telemetry
    registry and its sink labels; status-validated).  Default OFF —
    without this config no thread starts and no socket binds, and with
    it on the plane adds ZERO new JSONL fields and leaves dispatch
    counts untouched: it only reads state other subsystems already keep
    (docs/observability.md, "Live ops plane").

    Attributes:
        port: base TCP port; rank ``r`` binds ``port + r`` so colocated
            multihost ranks never collide.  ``0`` binds an ephemeral
            port (tests/benches; ``OpsPlane.port`` reports the bound
            one).  Status-validated to 0..65535.
        host: bind address — loopback by default so enabling the plane
            never exposes a run to the network without an explicit
            opt-in (``"0.0.0.0"`` for fleet scrapers behind a firewall).
        profile_default_seconds: capture length when ``/profile`` is hit
            without ``?seconds=`` (0 < default <= max;
            status-validated).
        profile_max_seconds: hard per-capture ceiling — a scraper asking
            for more gets this clamp, and the capture COUNT is already
            bounded by the attribution budget (status-validated > 0).
        requests_limit: row cap of the ``/requests`` table (> 0;
            status-validated); the response marks itself ``truncated``
            when in-flight requests exceed it.
    """

    port: int = 9200
    host: str = "127.0.0.1"
    profile_default_seconds: float = 2.0
    profile_max_seconds: float = 30.0
    requests_limit: int = 256


@dataclass
class ResilienceConfig:
    """Pod-scale resilience (ISSUE 7 tentpole): preemption-aware emergency
    checkpointing, integrity-verified auto-resume with quarantine, and the
    deterministic fault-injection harness.

    No reference equivalent (SURVEY.md §5: the reference's failure story is
    "crash = job death").  Millions-of-users scale means preemptible fleets
    and multi-day jobs: MLPerf-on-TPU-pods attributes most lost pod scaling
    to host-level disruption (arXiv:1909.09756), and the sharded per-host
    state of the ZeRO lineage (arXiv:2004.13336) makes "just restart it"
    a correctness problem a resume path must own.  Default OFF — without
    this config the step paths, signal dispositions, and checkpoint layout
    are untouched (bit-identical HLO, dispatch-count equal; the
    established guarantee).

    With it on:

    1. The preemption-notice signals set a flag; the facade finishes the
       in-flight optimizer step, drains async checkpoint threads, writes a
       synchronous **emergency checkpoint** (step counters + rng + loss-EMA
       + error-feedback residual in the extras) under ``save_path``, and
       exits with the distinct resumable ``exit_code``.
    2. Every checkpoint the facade writes additionally carries a
       ``manifest.json`` of per-file sha256 digests; ``Stoke.resume()``
       restores the newest tag that VERIFIES, quarantining (never
       deleting) corrupt or partial tags.
    3. ``resilience/*`` counters (preemptions, emergency saves, restarts,
       resumed/lost steps, quarantined tags) ride the telemetry registry
       and JSONL step events.
    4. The ``STOKE_CHAOS`` env var (or ``chaos`` here; config wins) arms
       the fault injector: ``kill_at_step=K`` (+ ``kill_mode=sigterm|
       sigkill|exception``), ``corrupt_save=N``, ``wedge_at_step=K`` (+
       ``wedge_s=S``).

    Attributes:
        save_path: emergency-checkpoint root directory (status-validated
            writable; also where ``Stoke.resume()`` looks first).
        save_name: tag name of emergency checkpoints (kept distinct from
            ``CheckpointConfig.auto_name`` so the two cadences never prune
            each other).
        preempt_signals: signal names treated as preemption notices.  With
            resilience on these mean "drain and save" — the flight
            recorder's dump-and-die SIGTERM disposition is superseded (the
            emergency path writes a better corpse: a loadable checkpoint
            plus a post-mortem bundle when a ``HealthConfig`` is present).
        exit_code: process exit code after a successful drain (must be
            1..255 and differ from the health watchdog's 113 so
            supervisors can classify drained-vs-hung; default 114).
            Only the default is in the stock supervisor's resumable set —
            a custom code must be paired with ``run_resilient.py
            --extra-resumable <code>`` or the supervisor classifies the
            clean drain as fatal and stops instead of restarting.
        exit_on_preempt: exit the process after the emergency save (the
            supervised-restart contract).  False raises
            :class:`~stoke_tpu.resilience.PreemptedError` instead —
            in-process drivers (tests, smoke) resume without a restart.
        manifest: write per-file digest manifests into every checkpoint
            this facade saves (emergency AND periodic/manual).
        verify_on_resume: validate digests during ``Stoke.resume()``
            discovery (manifest-less legacy tags stay acceptable).
        quarantine: move invalid tags to ``<root>/quarantine/`` during
            resume discovery instead of leaving them to shadow older
            valid tags.  Never deletes.
        max_to_keep: newest emergency tags kept under ``save_path``
            (pruned with the same in-flight-tag guard as every save).
        chaos: fault-injection spec (overrides the ``STOKE_CHAOS`` env
            var; None reads the env).  Parse errors are status errors.
    """

    save_path: str = "resilience_ckpts"
    save_name: str = "emergency"
    preempt_signals: Tuple[str, ...] = ("SIGTERM",)
    exit_code: int = 114
    exit_on_preempt: bool = True
    manifest: bool = True
    verify_on_resume: bool = True
    quarantine: bool = True
    max_to_keep: Optional[int] = 3
    chaos: Optional[str] = None


@dataclass
class CompileConfig:
    """Persistent compilation cache + AOT-lowered step programs (ISSUE 6
    tentpole).

    No reference equivalent (torch eager has no compile step to cache).
    TPU-native motivation: warm-up XLA compilation of the step programs is
    tens of seconds of pure ``goodput_compile_s`` on every restart of an
    identical job (arXiv:1810.09868 demonstrates full-AOT feasibility for
    exactly these programs; the TPU serving comparison arXiv:2605.25645
    attributes much of TPU's production edge to compile-and-cache
    discipline).  Default OFF — without this config the engine dispatches
    its ``jax.jit`` programs exactly as before, bit-identical HLO.

    With it on, three layers engage — all dispatching through ordinary
    ``jax.jit`` (donation, async dispatch, and numerics byte-for-byte
    the no-cache path):

    1. **Process program cache** (always with ``aot=True``): a second
       ``Stoke`` construction in the same process whose step programs
       lower to identical HLO dispatches through the first facade's
       already-compiled jit fns — zero recompilation, every backend.
    2. **XLA persistent cache** (``xla_cache=True``, non-CPU backends):
       the process-global jax compilation cache is pointed at
       ``<cache_dir>/xla`` so a warm PROCESS's backend compiles load
       from disk in milliseconds instead of re-running XLA codegen.
       Refused on CPU — this jaxlib's CPU cache serialization corrupts
       the heap for sharded/donated programs (the compile_cache module
       docstring pins the evidence).
    3. **AOT program ledger** (``aot=True``): each step program (accum /
       fused / window / multi / apply) is lowered at first dispatch and
       keyed by a sha256 of the **lowered HLO text** plus an environment
       fingerprint (jax/jaxlib versions, backend, ``XLA_FLAGS``,
       topology, process count — see
       ``stoke_tpu.compile_cache.environment_fingerprint``).  Per key, a
       ``<cache_dir>/exe-<key>.json`` provenance marker records the cold
       first-dispatch seconds; a warm start reports a
       ``compile_cache_hit``, credits the recorded seconds as reclaimed,
       and the goodput ledger splits its compile bucket into
       ``compile_fresh`` vs ``compile_cached``.  On a miss the compiled
       executable is additionally serialized to ``exe-<key>.bin`` as an
       offline AOT artifact (when a live XLA cache absorbs the extra
       compile).

    Step programs deliberately never dispatch through deserialized
    executables: on current jax, ``deserialize_and_load`` loses the
    donated-input bookkeeping, and chaining such calls over carried
    training state silently corrupts numerics (tests enforce the safe
    architecture).  Keying on the lowered HLO is what makes the ledger
    safe: any change in model code, loss math, optimizer hyperparameters
    (constants in the HLO), shapes, shardings, or precision changes the
    key — a warm start can never be served different math.

    Attributes:
        cache_dir: cache directory (created if missing; status-validated
            writable).  Shareable across runs/processes — entries are
            content-addressed and written atomically.
        aot: enable the AOT program ledger + process program cache
            (layers 1 and 3 above — warm-start serving, hit/miss
            accounting, serialized artifacts).
        xla_cache: point the process-global jax persistent compilation
            cache at ``<cache_dir>/xla`` (layer 2 above; non-CPU
            backends).  Process-global by nature; the FIRST run to
            install wins, and every later run in the process shares it
            (content-addressed, so sharing is always safe).
        serialize_executables: also write the ``exe-<key>.bin``
            serialized-executable artifact on each ledger miss (for
            offline AOT use; skipped automatically when no live XLA
            cache would absorb the extra compile).
        min_compile_time_s: only persist XLA-cache entries whose compile
            took at least this long (forwarded to
            ``jax_persistent_cache_min_compile_time_secs``; 0 caches
            everything — right for tests and the CPU mesh).
    """

    cache_dir: str = "compile_cache"
    aot: bool = True
    xla_cache: bool = True
    serialize_executables: bool = True
    min_compile_time_s: float = 0.0


#: prefill attention kernels ServeConfig accepts (validated by status.py)
SERVE_ATTENTION_KERNELS: Tuple[str, ...] = ("dense", "flash")
#: decode attention kernels ServeConfig accepts (ISSUE 13): "reference" is
#: the jnp gathered-block math (XLA-lowered), "pallas" the dedicated
#: streaming kernel (HBM→VMEM block walk; interpreter parity mode off-TPU)
SERVE_DECODE_KERNELS: Tuple[str, ...] = ("reference", "pallas")
#: weight-quantization modes ServeConfig accepts ("none" = serve at the
#: params' native dtype)
SERVE_QUANT_MODES: Tuple[str, ...] = ("none", "bf16", "int8")
#: KV-cache storage dtypes ServeConfig accepts
SERVE_KV_DTYPES: Tuple[str, ...] = ("float32", "bfloat16")


@dataclass
class ServeConfig:
    """Continuous-batching inference engine (ISSUE 9 tentpole): paged
    KV-cache, prefill/decode split, int8/bf16 weight quantization, and
    per-request TTFT/TPOT telemetry behind ``Stoke.serve()``.

    No reference equivalent (the reference is training-only; SURVEY.md has
    no inference story).  TPU serving economics hinge on exactly the pieces
    the training side already built — a fused attention kernel, aggressive
    batching, low-precision weights, and compile-and-cache discipline
    (arXiv:2605.25645, the Gemma-on-TPU serving comparison) — so the
    serving vertical reuses them: the flash kernel prefills, the PR-2
    stochastic-rounding quantizer (``parallel/collectives.py``) shrinks
    weights, the PR-6 AOT ledger warm-starts the prefill/decode programs,
    and the PR-1 registry carries the latency histograms.

    Default OFF — a ``ServeConfig`` in ``Stoke(configs=[...])`` changes
    NOTHING about the training paths (it is only read by
    ``Stoke.serve()``): training step-program HLO and dispatch counts are
    bit-identical with it absent vs present, and the ``serve/*`` telemetry
    fields never appear in a training run's JSONL.

    Four pillars (docs/serving.md has the full architecture):

    1. **Paged KV-cache** (``serving/kv_cache.py``): a block-pool cache of
       ``kv_blocks`` blocks × ``kv_block_size`` tokens, per-request block
       tables, addressed by the decode-mode attention variant
       (``ops.flash_attention.paged_decode_attention``).  Block 0 is a
       reserved scratch block (inactive slots write there; nothing reads
       it).
    2. **Continuous batching** (``serving/scheduler.py``): requests admit
       mid-flight into ``max_seqs`` fixed slots, finished sequences evict
       and their blocks refill the pool, so decode steps always run the
       full slot batch.
    3. **Prefill/decode split**: prompts prefill one request at a time
       (padded to ``prefill_pad_multiple`` buckets — the compiled-program
       count stays bounded) through the configured ``attention`` kernel;
       decode runs single-token cache-read steps.  Both programs register
       with the PR-6 compile-cache program ledger when a ``CompileConfig``
       is present.
    4. **Weight quantization** (``serving/quant.py``): ``quant="int8"``
       stores matmul weights as int8 + one f32 scale per
       ``quant_chunk_elems`` chunk (PR-2 ``quantize_chunks``), dequantized
       matmul-side inside the compiled programs — ~3.9× less HBM per
       replica; ``"bf16"`` halves instead.

    Attributes:
        max_seqs: decode slot count (the continuous-batching batch size;
            every decode step runs this fixed shape).
        kv_block_size: tokens per KV block.
        kv_blocks: total blocks in the pool, INCLUDING the reserved
            scratch block 0.  ``None`` auto-sizes to fit ``max_seqs``
            full-length sequences (+ scratch).
        max_seq_len: per-request prompt+output cap (must fit the model's
            ``max_len``; checked at ``serve()`` time).
        max_new_tokens: default per-request generation cap (requests may
            pass their own).
        prefill_pad_multiple: prompts are padded up to a multiple of this
            before prefill — each padded length is one compiled program,
            so this bounds program count (the "chunking" knob).
        attention: prefill kernel — "dense" (causal bias in fp32 softmax)
            or "flash" (the Pallas kernel, ``causal=True``; interpreted
            off-TPU).  Decode always reads the paged cache.
        decode_kernel: decode attention kernel (ISSUE 13) — "reference"
            (the jnp gathered-block math, XLA-lowered; bit-identical to
            the pre-fast-path engine) or "pallas"
            (``ops.flash_attention.paged_decode_attention_pallas``: the
            dedicated streaming kernel walking each request's block table
            HBM→VMEM).  Off-TPU a standalone engine auto-falls-back to
            the pallas INTERPRETER (the CPU parity mode tests pin against
            the reference); a real serve config declaring ``device='cpu'``
            is a status error instead.
        decode_pages_per_block / decode_block_h: the pallas decode
            kernel's block knobs (KV pages streamed per kernel step;
            heads per grid cell).  ``None`` = kernel defaults; both live
            in the autotune catalog (``decode_pages_per_block`` /
            ``decode_block_h``) for the ``--workload serve_decode``
            sweep.
        prefill_chunk_tokens: chunked prefill (ISSUE 13) — prompts longer
            than this prefill in fixed chunks of this many tokens,
            interleaved one chunk per engine iteration with decode steps,
            so a long prompt cannot stall in-flight requests' TPOT.
            Must be a multiple of ``prefill_pad_multiple`` (the bucket
            discipline that bounds compiled-program count; the chunk
            shape is ONE program).  ``None`` = unchunked (pre-fast-path
            behavior).
        sampling: compile the sampling-aware program variants (ISSUE 13):
            temperature / top-k / top-p drawn in-program from per-request
            seeded key streams.  Default False — the greedy engine's
            programs are bit-identical to pre-fast-path, and per-request
            ``SamplingParams`` are rejected at ``submit()``.
        temperature / top_k / top_p: default sampling knobs for requests
            that do not pass their own ``SamplingParams`` (temperature 0
            = exact greedy argmax; only read when ``sampling=True`` —
            non-default values without it are a status error, never
            silently ignored).
        sampling_seed: base of the deterministic per-request seed default
            (``sampling_seed + request_id`` when a request sets none), so
            whole runs replay from the config.
        kv_dtype: KV-cache storage dtype ("float32" for exact parity,
            "bfloat16" to halve cache HBM).
        quant: weight quantization mode ("none" | "bf16" | "int8").
        quant_chunk_elems: elements sharing one f32 scale in int8 mode
            (the PR-2 wire format; 128 ≈ 3.88× compression).
        quant_stochastic: unbiased stochastic rounding for int8 weights
            (the PR-2 machinery; default False = deterministic
            round-to-nearest — lower error for a one-shot weight cast).
        quant_min_size: leaves with fewer elements stay unquantized
            (biases/layernorms: quantizing them saves nothing and costs
            accuracy).
        eos_id: token id that finishes a request early (None = run to the
            token cap).
        log_every_n_steps: engine iterations between serve telemetry
            records (JSONL ``serve/*`` fields + gauge refresh).
        slo_ttft_target_s / slo_tpot_target_s: default SLO deadlines
            (ISSUE 16) for requests that carry a ``RequestSLO`` without
            their own targets — TTFT is arrival → first token (queue
            time included), TPOT the mean decode-token interval.  Both
            ``None`` by default: requests without a ``RequestSLO`` are
            never SLO-tracked, and an engine that sees none emits zero
            ``serve/slo_*`` JSONL fields with program HLO bit-identical
            to pre-ISSUE-16 (the tracker is purely host-side).
        speculative_k: speculative decoding (ISSUE 17) — draft up to this
            many tokens per request per decode iteration from the
            host-side prompt-lookup drafter and score them all in ONE
            verify dispatch (accepted run + one correction/bonus token
            emitted; >1 token per dispatch when drafts hit).  Requires
            ``sampling=True`` (the verify program rides the key-threaded
            sampling machinery; ``temperature=0.0`` keeps exact greedy
            streams — emitted streams bit-match the non-speculative
            engine in every mode).  ``None`` (default) = off, programs
            bit-identical to pre-ISSUE-17.  With chunked prefill, must
            satisfy ``speculative_k + 1 <= prefill_chunk_tokens`` (the
            verify query width stays within the chunk budget that bounds
            per-iteration work).
        speculative_ngram_max / speculative_ngram_min: the drafter's
            tail n-gram length bounds (longest tried first; see
            ``serving/speculative.py``).  Only read when
            ``speculative_k`` is set — non-default values without it are
            a status error, never silently ignored.
        verify_pages_per_block / verify_block_h: the pallas verify
            kernel's block knobs (autotune catalog entries
            ``verify_pages_per_block`` / ``verify_block_h`` under the
            ``serve_decode`` sweep).  Only read when ``speculative_k``
            is set AND ``decode_kernel="pallas"``; setting them outside
            that is a status error.
        cost_cards: serve roofline observatory (ISSUE 18) — attach one
            XLA cost analysis (FLOPs, bytes accessed, peak-HBM where
            available) to every serve program at the dispatch funnel,
            accumulate per-dispatch FLOP/byte counters, and derive the
            decode roofline (attainable TPOT, MFU, HBM-bandwidth
            utilization, per-program bound classification) plus the
            ``serve/cost_*`` JSONL block and the SLO tracker's
            TFLOP-goodput column.  Purely host-side: dispatched serve
            programs stay HLO bit-identical either way.  Requires an
            ``AttributionConfig`` in the run (its ``peak_tflops`` /
            ``peak_hbm_gbps`` are the roofline's ceilings) — the engine
            rejects ``cost_cards`` without one.
    """

    max_seqs: int = 8
    kv_block_size: int = 16
    kv_blocks: Optional[int] = None
    max_seq_len: int = 512
    max_new_tokens: int = 64
    prefill_pad_multiple: int = 64
    attention: str = "dense"
    decode_kernel: str = "reference"
    decode_pages_per_block: Optional[int] = None
    decode_block_h: Optional[int] = None
    prefill_chunk_tokens: Optional[int] = None
    sampling: bool = False
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    sampling_seed: int = 0
    kv_dtype: str = "float32"
    quant: str = "none"
    quant_chunk_elems: int = 128
    quant_stochastic: bool = False
    quant_min_size: int = 1024
    eos_id: Optional[int] = None
    log_every_n_steps: int = 8
    slo_ttft_target_s: Optional[float] = None
    slo_tpot_target_s: Optional[float] = None
    speculative_k: Optional[int] = None
    speculative_ngram_max: int = 3
    speculative_ngram_min: int = 1
    verify_pages_per_block: Optional[int] = None
    verify_block_h: Optional[int] = None
    cost_cards: bool = False


@dataclass
class ProfilerConfig:
    """First-class profiling (SURVEY.md §5: native win over the reference's
    DeepSpeed flops-profiler passthrough, configs.py:252-279).

    Attributes:
        trace_dir: where ``jax.profiler`` traces are written (serves the
            TensorBoard profile plugin / xprof).
        flops_estimate: log an XLA cost-analysis FLOPs estimate of the compiled
            train step (replaces DeepspeedFlopsConfig).
        wall_clock_breakdown: per-phase host timing of the facade calls
            (reference configs.py:540).
    """

    trace_dir: Optional[str] = None
    flops_estimate: bool = False
    wall_clock_breakdown: bool = False


# --------------------------------------------------------------------------- #
# Optimizer TypedDict (reference: configs.py:754-770)
# --------------------------------------------------------------------------- #


class StokeOptimizer(TypedDict):
    """Uninstantiated optimizer + kwargs (reference configs.py:754-770).

    ``optimizer`` is an optax transformation *constructor* (e.g. ``optax.sgd``,
    ``optax.adamw``); ``optimizer_kwargs`` its keyword args.  Mirrors the
    reference contract of passing ``torch.optim.SGD`` + kwargs so the facade
    owns instantiation (after sharding decisions are made).
    """

    optimizer: Callable[..., Any]
    optimizer_kwargs: Dict[str, Any]


# All config classes recognized by the status layer, keyed by class name
# (reference dedupe-by-class-name logic, status.py:321-343).
ALL_CONFIG_CLASSES: Tuple[type, ...] = (
    AttributionConfig,
    PrecisionConfig,
    ClipGradConfig,
    ClipGradNormConfig,
    CommConfig,
    CompileConfig,
    DataParallelConfig,
    MeshConfig,
    DistributedInitConfig,
    OSSConfig,
    SDDPConfig,
    FSDPConfig,
    OffloadOptimizerConfig,
    OffloadParamsConfig,
    OffloadDiskConfig,
    PartitionRulesConfig,
    ActivationCheckpointingConfig,
    CheckpointConfig,
    FleetConfig,
    HealthConfig,
    MemoryConfig,
    NumericsConfig,
    OpsPlaneConfig,
    ProfilerConfig,
    ResilienceConfig,
    ServeConfig,
    TelemetryConfig,
    TensorboardConfig,
    TraceConfig,
)


def asdict_config(cfg: Any) -> Dict[str, Any]:
    """Dataclass → plain dict with enums rendered to their values (used for
    status reporting + checkpoint metadata, reference status.py:629-654)."""
    if cfg is None:
        return {}
    out = {}
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if isinstance(v, Enum):
            v = v.value
        out[f.name] = v
    return out

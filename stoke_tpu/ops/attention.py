"""Sequence-parallel attention: ring attention and Ulysses all-to-all.

Long-context support the reference does not have (SURVEY.md §2.8).  Both
transforms shard the SEQUENCE dimension over a mesh axis so context length
scales with the number of devices; both are drop-in ``attention_fn``s for
``stoke_tpu.models.bert`` (same signature as ``dense_attention``).

- **Ring attention** (arxiv 2310.01889 pattern): Q stays put; K/V blocks
  rotate around the mesh axis via ``lax.ppermute`` while a flash-style
  online-softmax accumulator (running max ``m``, normalizer ``l``, weighted
  sum ``o``) folds in one K/V block per hop.  Peak memory per device is
  O(L_shard²) instead of O(L²), and the ppermute rides ICI neighbor links —
  the topology's cheapest collective.

- **Ulysses** (DeepSpeed-Ulysses pattern, arxiv 2309.14509): one
  ``all_to_all`` re-shards [B, H, L/n, D] → [B, H/n, L, D] (heads sharded,
  sequence gathered), runs ordinary dense attention locally, and a second
  ``all_to_all`` restores sequence sharding.  Cheaper collectives for
  moderate L; requires heads divisible by the axis size.

Both are written against ``shard_map`` (explicit per-shard code + explicit
collectives) and compose with the jit-GSPMD data-parallel engine: the mesh
carries ("data", "seq") axes and batch arrays are sharded over both.

**Inner kernel** (``inner=`` on every entry point): ``"flash"`` runs the
on-chip math through the Pallas flash kernel (``ops/flash_attention.py``) —
ring hops call flash with ``return_lse`` and merge partial attentions with
a log-sum-exp combine (per-device attention memory O(L·D·H/n), no score
materialization, vs the dense inner's O((L/n)²·H) score blocks); Ulysses
runs one flash call over the gathered sequence after the all-to-all, so
local memory is O(L·D·H/n) not O(L²·H/n).  ``"dense"`` keeps the einsum
inner math (useful for debugging and as the numerics reference).  The
default ``"auto"`` picks flash whenever the local length fits the flash
block ladder (L ≤ 512 or divisible by a candidate) and dense otherwise, so
pre-existing call sites keep working for any L.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .flash_attention import DEFAULT_BLOCK_Q, _pick_block, flash_attention

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map_fn

    def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
        return _shard_map_fn(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
        return _shard_map_old(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )

_NEG_INF = -1e30


def _resolve_inner(inner: str, L: int) -> str:
    """Resolve the inner-kernel choice.  ``"auto"`` (the default) uses flash
    when the flash block picker supports the local length L and falls back
    to the dense einsum otherwise (flash needs L ≤ 512 or L divisible by a
    block candidate); explicit ``"flash"``/``"dense"`` are honored verbatim
    (flash will raise its actionable block error for unsupported L)."""
    if inner not in ("auto", "flash", "dense"):
        raise ValueError(
            f"inner must be 'auto', 'flash' or 'dense', got {inner!r}"
        )
    if inner != "auto":
        return inner
    try:
        _pick_block(None, L, DEFAULT_BLOCK_Q)
        return "flash"
    except ValueError:
        return "dense"


def _resolve_batch_axis(q, mesh, axis_name, batch_axis) -> Optional[str]:
    """Shard the batch over ``batch_axis`` when possible; replicate when the
    axis is absent or the batch is not divisible (e.g. tiny init-tracing
    batches).  The sequence axis is mandatory — raise if L doesn't divide."""
    if q.shape[2] % mesh.shape[axis_name] != 0:
        raise ValueError(
            f"sequence length {q.shape[2]} not divisible by mesh axis "
            f"'{axis_name}' size {mesh.shape[axis_name]}; pad the sequence"
        )
    if not batch_axis or batch_axis not in mesh.axis_names:
        return None
    if q.shape[0] % mesh.shape[batch_axis] != 0:
        return None
    return batch_axis


def _online_softmax_block(o, m, l, scores, v):
    """Fold one [.., Lq, Lk_blk] score block into the flash accumulator."""
    m_blk = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # correction for previously accumulated blocks
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    # fully-masked blocks: exp(-inf - (-inf)) would be 1; force true zeros
    p = jnp.where(scores > _NEG_INF * 0.5, p, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(p.dtype)
    )
    return o_new, m_new, l_new


def _ring_shard(q, k, v, kmask, *, axis_name, causal, scale):
    """Per-shard ring attention body (runs inside shard_map).

    q: [B, H, Lq, D] (this device's query block, stays resident)
    k, v: [B, H, Lk, D] (rotating blocks)
    kmask: [B, Lk] 0/1 key-validity (rotates with k/v), or None
    """
    size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    qf = q.astype(jnp.float32)
    scale = jnp.float32(scale)

    q_pos = my_idx * Lq + jnp.arange(Lq)  # global query positions

    o0 = jnp.zeros((B, H, Lq, D), jnp.float32)
    m0 = jnp.full((B, H, Lq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Lq), jnp.float32)
    perm = [(i, (i + 1) % size) for i in range(size)]

    def body(step, carry):
        o, m, l, k, v, kmask = carry
        # which shard's K/V do we currently hold?
        src = (my_idx - step) % size
        scores = jnp.einsum("bhqd,bhkd->bhqk", qf, k.astype(jnp.float32)) * scale
        if kmask is not None:
            scores = jnp.where(kmask[:, None, None, :] > 0, scores, _NEG_INF)
        if causal:
            k_pos = src * Lk + jnp.arange(Lk)
            scores = jnp.where(
                q_pos[:, None] >= k_pos[None, :], scores, _NEG_INF
            )
        o, m, l = _online_softmax_block(o, m, l, scores, v)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        if kmask is not None:
            kmask = lax.ppermute(kmask, axis_name, perm)
        return o, m, l, k, v, kmask

    o, m, l, *_ = lax.fori_loop(0, size, body, (o0, m0, l0, k, v, kmask))
    # fully-masked rows (all padding) have l == 0; emit zeros, not NaN
    safe_l = jnp.where(l > 0, l, 1.0)
    return (o / safe_l[..., None]).astype(q.dtype)


def _rotate_kv(k, v, km, axis_name, perm):
    """One ring hop: pass K/V (and the rotating key mask) to the neighbor."""
    k = lax.ppermute(k, axis_name, perm)
    v = lax.ppermute(v, axis_name, perm)
    if km is not None:
        km = lax.ppermute(km, axis_name, perm)
    return k, v, km


def _seq_shard_map(body, mesh, qkv_spec, mask_spec, q, k, v, kmask):
    """Dispatch a per-shard attention body through shard_map with the
    standard (q, k, v[, kmask]) signature (kmask=None drops the operand)."""
    if kmask is None:
        fn = shard_map(
            lambda q, k, v: body(q, k, v, None),
            mesh, in_specs=(qkv_spec, qkv_spec, qkv_spec), out_specs=qkv_spec,
        )
        return fn(q, k, v)
    fn = shard_map(
        lambda q, k, v, km: body(q, k, v, km),
        mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
    )
    return fn(q, k, v, kmask)


def _lse_merge(o, lse, o_hop, lse_hop):
    """Log-sum-exp merge of two partial attentions.  The finite ``-NEG_INF``
    sentinel keeps every term finite (fully-masked hops get weight
    ``exp(-huge) == 0.0`` exactly)."""
    lse_new = jnp.logaddexp(lse, lse_hop)
    o_new = (
        o * jnp.exp(lse - lse_new)[..., None]
        + o_hop.astype(jnp.float32) * jnp.exp(lse_hop - lse_new)[..., None]
    )
    return o_new, lse_new


def _ring_shard_flash(q, k, v, kmask, *, axis_name, causal, size):
    """Per-shard ring attention with the Pallas flash kernel as the hop math.

    Each hop runs flash attention on the resident Q block against the
    currently-held K/V block (``return_lse``), and partial attentions merge
    via the log-sum-exp combine ``o = o·e^{lse-lse'} + o_hop·e^{lse_hop-lse'}``.
    Gradients flow through both flash outputs (the lse cotangent folds into
    the flash backward kernels — see ``_flash_backward``).

    Hop 0 (the diagonal — this device's own K/V block) runs outside the loop
    so the causal flag can be static (causal-local flash); hops 1..size-1
    share ONE flash instance inside a ``fori_loop`` — compile time and
    executable size stay constant in the axis size.  At hop ``step`` this
    device holds the K/V block of source shard ``(my_idx - step) % size``,
    which for a causal mask contributes fully iff ``step <= my_idx`` (all
    its positions are strictly earlier) — enforced with a traced key mask
    that zeroes non-contributing hops (flash emits lse = -NEG_INF for
    fully-masked rows, making the merge a no-op).
    """
    my_idx = lax.axis_index(axis_name)
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    perm = [(i, (i + 1) % size) for i in range(size)]

    # hop 0: diagonal block, static causal flag
    o_hop, lse_hop = flash_attention(
        q, k, v, kmask, causal=causal, return_lse=True
    )
    o = o_hop.astype(jnp.float32)
    lse = lse_hop

    def body(step, carry):
        o, lse, k, v, km = carry
        k, v, km = _rotate_kv(k, v, km, axis_name, perm)
        hop_mask = km
        if causal:
            valid = (step <= my_idx).astype(jnp.int32)
            vm = jnp.broadcast_to(valid, (B, Lk))
            hop_mask = vm if hop_mask is None else hop_mask * vm
        o_hop, lse_hop = flash_attention(
            q, k, v, hop_mask, causal=False, return_lse=True
        )
        o, lse = _lse_merge(o, lse, o_hop, lse_hop)
        return o, lse, k, v, km

    if size > 1:
        # carry km as an explicit array only when a mask exists; fori_loop
        # needs a uniform carry structure
        if kmask is not None:
            o, lse, *_ = lax.fori_loop(1, size, body, (o, lse, k, v, kmask))
        else:
            def body_nomask(step, carry):
                o, lse, k, v = carry
                o, lse, k2, v2, _ = body(step, (o, lse, k, v, None))
                return o, lse, k2, v2

            o, lse, *_ = lax.fori_loop(1, size, body_nomask, (o, lse, k, v))
    return o.astype(q.dtype)


def ring_attention(
    q, k, v, kmask=None, *, mesh: Mesh, axis_name: str = "seq",
    causal: bool = False, batch_axis: Optional[str] = "data",
    inner: str = "auto",
):
    """Ring attention over sequence shards.

    Args:
        q, k, v: [B, H, L, D] logically-global arrays (sharded over
            ``axis_name`` on the L dim and optionally ``batch_axis`` on B).
        kmask: optional [B, L] key-validity mask (1 = attend).
        mesh: the device mesh holding ``axis_name`` (and ``batch_axis``).
        causal: apply a causal (autoregressive) mask using global positions.
        inner: per-hop kernel — "auto" (flash when the per-shard length
            supports it, else dense), "flash" (Pallas, blockwise), or
            "dense" (einsum reference).

    Returns [B, H, L, D] with the same sharding as ``q``.
    """
    inner = _resolve_inner(inner, q.shape[2] // mesh.shape[axis_name])
    ba = _resolve_batch_axis(q, mesh, axis_name, batch_axis)
    qkv_spec = P(ba, None, axis_name, None)
    mask_spec = P(ba, axis_name)
    if inner == "flash":
        body = functools.partial(
            _ring_shard_flash,
            axis_name=axis_name,
            causal=causal,
            size=mesh.shape[axis_name],
        )
    else:
        body = functools.partial(
            _ring_shard,
            axis_name=axis_name,
            causal=causal,
            scale=1.0 / (q.shape[-1] ** 0.5),
        )
    return _seq_shard_map(body, mesh, qkv_spec, mask_spec, q, k, v, kmask)


# --------------------------------------------------------------------------- #
# zigzag ring attention (causal load balance)
# --------------------------------------------------------------------------- #


def zigzag_permutation(L: int, size: int):
    """Index permutation mapping the natural sequence order to the zigzag
    layout: with 2·size blocks of length L/(2·size), device d's shard is
    ``concat(block_d, block_{2·size-1-d})``.  Apply with
    ``x.take(perm, axis=seq_axis)``; invert with ``inverse_permutation``."""
    if L % (2 * size):
        raise ValueError(
            f"zigzag layout needs L divisible by 2*axis_size = {2 * size}, "
            f"got {L}"
        )
    Lb = L // (2 * size)
    blocks = []
    for d in range(size):
        blocks.append(np.arange(d * Lb, (d + 1) * Lb))
        hi = 2 * size - 1 - d
        blocks.append(np.arange(hi * Lb, (hi + 1) * Lb))
    return np.concatenate(blocks)


def inverse_permutation(perm):
    """Inverse of an index permutation: ``x[perm][inverse_permutation(perm)]
    == x`` (used to undo the zigzag sequence layout host-side)."""
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm))
    return inv


def _zigzag_shard(q, k, v, kmask, *, axis_name, size):
    """Per-shard zigzag causal ring (flash inner).

    The contiguous causal ring is load-IMBALANCED: at hop ``step`` only
    devices with index ≥ step contribute, so half the hop FLOPs are masked
    away on average.  In the zigzag layout device d holds sequence blocks
    ``(d, 2n-1-d)`` — one early, one late — so every device does the same
    causal work at every hop (the ring-flash-attention / striped-attention
    balance trick).

    Per hop the held K/V pair (two blocks) meets the resident Q pair:
    block-level causality is whole-block (full / none) except the two
    diagonal pairs of hop 0, which run as static causal-local flash calls.
    Later hops are three square flash calls — q_lo x k_lo, q_hi x k_lo,
    q_hi x k_hi — with traced whole-block validity masks; the fourth pair
    (q_lo x k_hi) is STATICALLY invisible (a hi key block 2n-1-src >= n can
    never precede a lo query block my <= n-1) and is skipped entirely.
    """
    my = lax.axis_index(axis_name)
    B, H, Lq2, D = q.shape
    Lb = Lq2 // 2
    n = size
    perm = [(i, (i + 1) % n) for i in range(n)]

    my_lo = my
    my_hi = 2 * n - 1 - my

    q_lo, q_hi = q[:, :, :Lb], q[:, :, Lb:]

    def flash_lse(qh, kk, vv, mask, causal_flag):
        return flash_attention(qh, kk, vv, mask, causal=causal_flag,
                               return_lse=True)

    # ---- hop 0: own blocks.  lo×lo and hi×hi are the causal diagonals;
    # hi×lo is fully visible (my_hi > my_lo always); lo×hi contributes
    # nothing.
    k_lo, k_hi = k[:, :, :Lb], k[:, :, Lb:]
    v_lo, v_hi = v[:, :, :Lb], v[:, :, Lb:]
    m_lo = None if kmask is None else kmask[:, :Lb]
    m_hi = None if kmask is None else kmask[:, Lb:]
    o_lo, lse_lo = flash_lse(q_lo, k_lo, v_lo, m_lo, True)
    o_hi, lse_hi = flash_lse(q_hi, k_hi, v_hi, m_hi, True)
    o_hi = o_hi.astype(jnp.float32)
    o_hi, lse_hi = _lse_merge(
        o_hi, lse_hi, *flash_lse(q_hi, k_lo, v_lo, m_lo, False)
    )
    o_lo = o_lo.astype(jnp.float32)

    # ---- hops 1..n-1: held blocks are (src, 2n-1-src); all visibility is
    # whole-block (full or none — a traced scalar), so each (Q half,
    # K half) pair is one square flash call whose key mask broadcasts the
    # pair's validity (an invisible pair yields lse = -NEG_INF and the
    # merge is an exact no-op).
    def body(step, carry):
        o_lo, lse_lo, o_hi, lse_hi, k, v, km = carry
        k, v, km = _rotate_kv(k, v, km, axis_name, perm)
        src = (my - step) % n
        src_blks = (src, 2 * n - 1 - src)
        k_halves = (k[:, :, :Lb], k[:, :, Lb:])
        v_halves = (v[:, :, :Lb], v[:, :, Lb:])
        km_halves = (None, None) if km is None else (km[:, :Lb], km[:, Lb:])

        def pair(o, lse, qh, q_blk, half):
            vis = (src_blks[half] < q_blk).astype(jnp.int32)
            mask = jnp.broadcast_to(vis, (B, Lb))
            if km_halves[half] is not None:
                mask = mask * km_halves[half]
            return _lse_merge(
                o, lse,
                *flash_lse(qh, k_halves[half], v_halves[half], mask, False),
            )

        # q_lo sees only lo key blocks (hi blocks are statically later)
        o_lo, lse_lo = pair(o_lo, lse_lo, q_lo, my_lo, 0)
        o_hi, lse_hi = pair(o_hi, lse_hi, q_hi, my_hi, 0)
        o_hi, lse_hi = pair(o_hi, lse_hi, q_hi, my_hi, 1)
        return o_lo, lse_lo, o_hi, lse_hi, k, v, km

    if n > 1:
        if kmask is not None:
            o_lo, lse_lo, o_hi, lse_hi, *_ = lax.fori_loop(
                1, n, body, (o_lo, lse_lo, o_hi, lse_hi, k, v, kmask)
            )
        else:
            def body_nomask(step, carry):
                o_lo, lse_lo, o_hi, lse_hi, k, v = carry
                o_lo, lse_lo, o_hi, lse_hi, k2, v2, _ = body(
                    step, (o_lo, lse_lo, o_hi, lse_hi, k, v, None)
                )
                return o_lo, lse_lo, o_hi, lse_hi, k2, v2

            o_lo, lse_lo, o_hi, lse_hi, *_ = lax.fori_loop(
                1, n, body_nomask, (o_lo, lse_lo, o_hi, lse_hi, k, v)
            )
    return jnp.concatenate([o_lo, o_hi], axis=2).astype(q.dtype)


def zigzag_ring_attention(
    q, k, v, kmask=None, *, mesh: Mesh, axis_name: str = "seq",
    batch_axis: Optional[str] = "data",
):
    """Load-balanced CAUSAL ring attention over the zigzag layout.

    Inputs must already be in zigzag order along the sequence dim (use
    :func:`zigzag_permutation` once at the data layer — positions/RoPE and
    targets must be permuted consistently); the output is returned in the
    same layout.  Requires ``L % (2·axis_size) == 0``.  Always causal
    (the zigzag layout exists to balance the causal mask's work) and always
    flash-inner.  ``kmask`` follows the same layout.
    """
    L = q.shape[2]
    size = mesh.shape[axis_name]
    if L % (2 * size):
        raise ValueError(
            f"zigzag layout needs L divisible by 2*axis_size = {2 * size}, "
            f"got {L}"
        )
    ba = _resolve_batch_axis(q, mesh, axis_name, batch_axis)
    qkv_spec = P(ba, None, axis_name, None)
    mask_spec = P(ba, axis_name)
    body = functools.partial(_zigzag_shard, axis_name=axis_name, size=size)
    return _seq_shard_map(body, mesh, qkv_spec, mask_spec, q, k, v, kmask)


def _ulysses_shard(q, k, v, kmask, *, axis_name, causal, scale, inner):
    """Per-shard Ulysses body: all_to_all to head-sharding, local attention
    (flash or dense), all_to_all back.  q/k/v: [B, H, Ls, D] with H the FULL
    head count."""
    # [B, H, Ls, D] -> [B, H/n, L, D]: split heads (axis 1), concat seq (axis 2)
    qh = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    km = None
    if kmask is not None:
        km = lax.all_gather(kmask, axis_name, axis=1, tiled=True)  # [B, L]
    if inner == "flash":
        # local attention is a full flash call: no [L, L] score tensor, so
        # per-device memory after the all-to-all is O(L·D·H/n) not O(L²·H/n)
        out = flash_attention(qh, kh, vh, km, causal=causal)
    else:
        L = qh.shape[2]
        scores = (
            jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                       kh.astype(jnp.float32)) * scale
        )
        if km is not None:
            scores = jnp.where(km[:, None, None, :] > 0, scores, _NEG_INF)
        if causal:
            pos = jnp.arange(L)
            scores = jnp.where(pos[:, None] >= pos[None, :], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh.astype(jnp.float32))
    # [B, H/n, L, D] -> [B, H, Ls, D]
    out = lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1, tiled=True)
    return out.astype(q.dtype)


def ulysses_attention(
    q, k, v, kmask=None, *, mesh: Mesh, axis_name: str = "seq",
    causal: bool = False, batch_axis: Optional[str] = "data",
    inner: str = "auto",
):
    """DeepSpeed-Ulysses-style all-to-all sequence parallelism (head count
    must be divisible by the mesh axis size).  Same contract as
    :func:`ring_attention`; ``inner`` selects the local attention kernel
    after the all-to-all over the full gathered length ("auto" default =
    flash when supported, "flash", or "dense")."""
    inner = _resolve_inner(inner, q.shape[2])
    size = mesh.shape[axis_name]
    if q.shape[1] % size != 0:
        raise ValueError(
            f"ulysses_attention: heads ({q.shape[1]}) not divisible by "
            f"mesh axis '{axis_name}' size ({size})"
        )
    ba = _resolve_batch_axis(q, mesh, axis_name, batch_axis)
    qkv_spec = P(ba, None, axis_name, None)
    mask_spec = P(ba, axis_name)
    body = functools.partial(
        _ulysses_shard,
        axis_name=axis_name,
        causal=causal,
        scale=1.0 / (q.shape[-1] ** 0.5),
        inner=inner,
    )
    return _seq_shard_map(body, mesh, qkv_spec, mask_spec, q, k, v, kmask)


def _as_model_attention(impl, mesh, axis_name, batch_axis, causal, inner):
    """Adapt ring/ulysses to the ``dense_attention`` signature used by
    stoke_tpu.models.bert (q/k/v [B,H,L,D] + additive bias)."""

    def attention_fn(q, k, v, bias, dropout_rng=None, dropout_rate=0.0,
                     deterministic=True):
        if dropout_rate > 0.0 and not deterministic:
            raise NotImplementedError(
                "sequence-parallel attention does not support attention-prob "
                "dropout; set attention dropout to 0 (residual dropout is fine)"
            )
        kmask = None
        if bias is not None:
            if bias.shape[-2] > 1:
                # a full [.., L, L] bias (an in-model causal mask) would be
                # silently misread as a key mask of its first row — refuse
                raise ValueError(
                    "sequence-parallel attention received a full [.., L, L] "
                    "attention bias (an in-model causal mask?); these "
                    "adapters support only [B, 1, 1, L] key-padding biases "
                    "— set attention_is_causal=True on the model and let "
                    "the attention enforce causality"
                )
            # recover the [B, L] key mask from the additive [B,1,1,L] bias
            kmask = (bias[:, 0, 0, :] > -1e8).astype(jnp.int32)
        return impl(
            q, k, v, kmask, mesh=mesh, axis_name=axis_name,
            causal=causal, batch_axis=batch_axis, inner=inner,
        )

    return attention_fn


def make_ring_attention(
    mesh: Mesh, axis_name: str = "seq", batch_axis: str = "data",
    causal: bool = False, inner: str = "auto",
) -> Callable:
    """Build a ring-attention ``attention_fn`` pluggable into
    ``BertEncoder(attention_fn=...)``."""
    return _as_model_attention(
        ring_attention, mesh, axis_name, batch_axis, causal, inner
    )


def make_ulysses_attention(
    mesh: Mesh, axis_name: str = "seq", batch_axis: str = "data",
    causal: bool = False, inner: str = "auto",
) -> Callable:
    """Build a Ulysses ``attention_fn`` pluggable into
    ``BertEncoder(attention_fn=...)``."""
    return _as_model_attention(
        ulysses_attention, mesh, axis_name, batch_axis, causal, inner
    )


def make_zigzag_ring_attention(
    mesh: Mesh, axis_name: str = "seq", batch_axis: str = "data",
) -> Callable:
    """Build a zigzag-ring ``attention_fn`` (always causal, flash-inner).

    The MODEL must run on zigzag-ordered sequences: permute tokens/masks
    with :func:`zigzag_permutation` at the data layer and pass the
    permutation as the model's position ids (``GPT(..., positions=perm)``)
    so position embeddings follow original positions.  Set
    ``attention_is_causal=True`` — causality is enforced here, by original
    positions."""

    def impl(q, k, v, kmask, *, mesh, axis_name, causal, batch_axis, inner):
        # zigzag is always causal and flash-inner; the extra kwargs exist
        # only to fit the shared adapter signature
        return zigzag_ring_attention(
            q, k, v, kmask, mesh=mesh, axis_name=axis_name,
            batch_axis=batch_axis,
        )

    return _as_model_attention(
        impl, mesh, axis_name, batch_axis, causal=True, inner="flash"
    )

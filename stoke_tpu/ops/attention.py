"""Sequence-parallel attention: ring attention and Ulysses all-to-all.

Long-context support the reference does not have (SURVEY.md §2.8).  Both
transforms shard the SEQUENCE dimension over a mesh axis so context length
scales with the number of devices; both are drop-in ``attention_fn``s for
``stoke_tpu.models.bert`` (same signature as ``dense_attention``).

- **Ring attention** (arxiv 2310.01889 pattern): Q stays put; K/V blocks
  rotate around the mesh axis via ``lax.ppermute`` while a flash-style
  online-softmax accumulator (running max ``m``, normalizer ``l``, weighted
  sum ``o``) folds in one K/V block per hop.  Peak memory per device is
  O(L_shard²) instead of O(L²), and the ppermute rides ICI neighbor links —
  the topology's cheapest collective.

- **Ulysses** (DeepSpeed-Ulysses pattern, arxiv 2309.14509): one
  ``all_to_all`` re-shards [B, H, L/n, D] → [B, H/n, L, D] (heads sharded,
  sequence gathered), runs ordinary dense attention locally, and a second
  ``all_to_all`` restores sequence sharding.  Cheaper collectives for
  moderate L; requires heads divisible by the axis size.

Both are written against ``shard_map`` (explicit per-shard code + explicit
collectives) and compose with the jit-GSPMD data-parallel engine: the mesh
carries ("data", "seq") axes and batch arrays are sharded over both.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map_fn

    def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
        return _shard_map_fn(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
        return _shard_map_old(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )

_NEG_INF = -1e30


def _resolve_batch_axis(q, mesh, axis_name, batch_axis) -> Optional[str]:
    """Shard the batch over ``batch_axis`` when possible; replicate when the
    axis is absent or the batch is not divisible (e.g. tiny init-tracing
    batches).  The sequence axis is mandatory — raise if L doesn't divide."""
    if q.shape[2] % mesh.shape[axis_name] != 0:
        raise ValueError(
            f"sequence length {q.shape[2]} not divisible by mesh axis "
            f"'{axis_name}' size {mesh.shape[axis_name]}; pad the sequence"
        )
    if not batch_axis or batch_axis not in mesh.axis_names:
        return None
    if q.shape[0] % mesh.shape[batch_axis] != 0:
        return None
    return batch_axis


def _online_softmax_block(o, m, l, scores, v):
    """Fold one [.., Lq, Lk_blk] score block into the flash accumulator."""
    m_blk = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # correction for previously accumulated blocks
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    # fully-masked blocks: exp(-inf - (-inf)) would be 1; force true zeros
    p = jnp.where(scores > _NEG_INF * 0.5, p, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(p.dtype)
    )
    return o_new, m_new, l_new


def _ring_shard(q, k, v, kmask, *, axis_name, causal, scale):
    """Per-shard ring attention body (runs inside shard_map).

    q: [B, H, Lq, D] (this device's query block, stays resident)
    k, v: [B, H, Lk, D] (rotating blocks)
    kmask: [B, Lk] 0/1 key-validity (rotates with k/v), or None
    """
    size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    qf = q.astype(jnp.float32)
    scale = jnp.float32(scale)

    q_pos = my_idx * Lq + jnp.arange(Lq)  # global query positions

    o0 = jnp.zeros((B, H, Lq, D), jnp.float32)
    m0 = jnp.full((B, H, Lq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Lq), jnp.float32)
    perm = [(i, (i + 1) % size) for i in range(size)]

    def body(step, carry):
        o, m, l, k, v, kmask = carry
        # which shard's K/V do we currently hold?
        src = (my_idx - step) % size
        scores = jnp.einsum("bhqd,bhkd->bhqk", qf, k.astype(jnp.float32)) * scale
        if kmask is not None:
            scores = jnp.where(kmask[:, None, None, :] > 0, scores, _NEG_INF)
        if causal:
            k_pos = src * Lk + jnp.arange(Lk)
            scores = jnp.where(
                q_pos[:, None] >= k_pos[None, :], scores, _NEG_INF
            )
        o, m, l = _online_softmax_block(o, m, l, scores, v)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        if kmask is not None:
            kmask = lax.ppermute(kmask, axis_name, perm)
        return o, m, l, k, v, kmask

    o, m, l, *_ = lax.fori_loop(0, size, body, (o0, m0, l0, k, v, kmask))
    # fully-masked rows (all padding) have l == 0; emit zeros, not NaN
    safe_l = jnp.where(l > 0, l, 1.0)
    return (o / safe_l[..., None]).astype(q.dtype)


def ring_attention(
    q, k, v, kmask=None, *, mesh: Mesh, axis_name: str = "seq",
    causal: bool = False, batch_axis: Optional[str] = "data",
):
    """Ring attention over sequence shards.

    Args:
        q, k, v: [B, H, L, D] logically-global arrays (sharded over
            ``axis_name`` on the L dim and optionally ``batch_axis`` on B).
        kmask: optional [B, L] key-validity mask (1 = attend).
        mesh: the device mesh holding ``axis_name`` (and ``batch_axis``).
        causal: apply a causal (autoregressive) mask using global positions.

    Returns [B, H, L, D] with the same sharding as ``q``.
    """
    ba = _resolve_batch_axis(q, mesh, axis_name, batch_axis)
    qkv_spec = P(ba, None, axis_name, None)
    mask_spec = P(ba, axis_name)
    body = functools.partial(
        _ring_shard,
        axis_name=axis_name,
        causal=causal,
        scale=1.0 / (q.shape[-1] ** 0.5),
    )
    if kmask is None:
        fn = shard_map(
            lambda q, k, v: body(q, k, v, None),
            mesh, in_specs=(qkv_spec, qkv_spec, qkv_spec), out_specs=qkv_spec,
        )
        return fn(q, k, v)
    fn = shard_map(
        lambda q, k, v, km: body(q, k, v, km),
        mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
    )
    return fn(q, k, v, kmask)


def _ulysses_shard(q, k, v, kmask, *, axis_name, causal, scale):
    """Per-shard Ulysses body: all_to_all to head-sharding, dense attention,
    all_to_all back.  q/k/v: [B, H, Ls, D] with H the FULL head count."""
    size = lax.psum(1, axis_name)
    # [B, H, Ls, D] -> [B, H/n, L, D]: split heads (axis 1), concat seq (axis 2)
    qh = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    if kmask is not None:
        km = lax.all_gather(kmask, axis_name, axis=1, tiled=True)  # [B, L]
    L = qh.shape[2]
    scores = (
        jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                   kh.astype(jnp.float32)) * scale
    )
    if kmask is not None:
        scores = jnp.where(km[:, None, None, :] > 0, scores, _NEG_INF)
    if causal:
        pos = jnp.arange(L)
        scores = jnp.where(pos[:, None] >= pos[None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh.astype(jnp.float32))
    # [B, H/n, L, D] -> [B, H, Ls, D]
    out = lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1, tiled=True)
    return out.astype(q.dtype)


def ulysses_attention(
    q, k, v, kmask=None, *, mesh: Mesh, axis_name: str = "seq",
    causal: bool = False, batch_axis: Optional[str] = "data",
):
    """DeepSpeed-Ulysses-style all-to-all sequence parallelism (head count
    must be divisible by the mesh axis size).  Same contract as
    :func:`ring_attention`."""
    size = mesh.shape[axis_name]
    if q.shape[1] % size != 0:
        raise ValueError(
            f"ulysses_attention: heads ({q.shape[1]}) not divisible by "
            f"mesh axis '{axis_name}' size ({size})"
        )
    ba = _resolve_batch_axis(q, mesh, axis_name, batch_axis)
    qkv_spec = P(ba, None, axis_name, None)
    mask_spec = P(ba, axis_name)
    body = functools.partial(
        _ulysses_shard,
        axis_name=axis_name,
        causal=causal,
        scale=1.0 / (q.shape[-1] ** 0.5),
    )
    if kmask is None:
        fn = shard_map(
            lambda q, k, v: body(q, k, v, None),
            mesh, in_specs=(qkv_spec, qkv_spec, qkv_spec), out_specs=qkv_spec,
        )
        return fn(q, k, v)
    fn = shard_map(
        lambda q, k, v, km: body(q, k, v, km),
        mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
    )
    return fn(q, k, v, kmask)


def _as_model_attention(impl, mesh, axis_name, batch_axis, causal):
    """Adapt ring/ulysses to the ``dense_attention`` signature used by
    stoke_tpu.models.bert (q/k/v [B,H,L,D] + additive bias)."""

    def attention_fn(q, k, v, bias, dropout_rng=None, dropout_rate=0.0,
                     deterministic=True):
        if dropout_rate > 0.0 and not deterministic:
            raise NotImplementedError(
                "sequence-parallel attention does not support attention-prob "
                "dropout; set attention dropout to 0 (residual dropout is fine)"
            )
        kmask = None
        if bias is not None:
            # recover the [B, L] key mask from the additive [B,1,1,L] bias
            kmask = (bias[:, 0, 0, :] > -1e8).astype(jnp.int32)
        return impl(
            q, k, v, kmask, mesh=mesh, axis_name=axis_name,
            causal=causal, batch_axis=batch_axis,
        )

    return attention_fn


def make_ring_attention(
    mesh: Mesh, axis_name: str = "seq", batch_axis: str = "data",
    causal: bool = False,
) -> Callable:
    """Build a ring-attention ``attention_fn`` pluggable into
    ``BertEncoder(attention_fn=...)``."""
    return _as_model_attention(ring_attention, mesh, axis_name, batch_axis, causal)


def make_ulysses_attention(
    mesh: Mesh, axis_name: str = "seq", batch_axis: str = "data",
    causal: bool = False,
) -> Callable:
    """Build a Ulysses ``attention_fn`` pluggable into
    ``BertEncoder(attention_fn=...)``."""
    return _as_model_attention(ulysses_attention, mesh, axis_name, batch_axis, causal)

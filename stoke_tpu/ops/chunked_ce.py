"""Chunked LM-head cross entropy: loss without the [B, L, V] logits tensor.

At real LM scale the other long-context memory cliff (besides attention) is
the output head: materializing logits costs B·L·V activations — at L=8k,
V=50k, bf16 that is ~0.8 GB per sample *before* the softmax residuals.  The
reference has no equivalent (it ships no models, SURVEY.md §2.7).

TPU-idiomatic fix: ``lax.scan`` over sequence chunks with rematerialization.
Each step computes the chunk's logits on the MXU ([B, c, H] × [V, H]),
reduces them to cross-entropy sums, and drops them; ``jax.checkpoint``
around the scan body keeps the backward residuals to the chunk inputs, so
peak live logits memory is O(B·chunk·V) for forward AND backward — L/chunk
times smaller — while the per-chunk GEMMs stay MXU-sized.

Pairs with ``GPT(chunked_head=True)``, which returns ``(hidden, embedding)``
instead of logits; :func:`chunked_causal_lm_loss` is the drop-in loss for
that output (same semantics as ``models.gpt.causal_lm_loss``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def chunked_softmax_cross_entropy(
    hidden, emb, targets, *, chunk: int = 128, mask=None,
):
    """Masked-mean token cross entropy from hidden states and an embedding.

    Args:
        hidden: [B, L, H] final hidden states.
        emb: [V, H] (tied) output embedding matrix.
        targets: [B, L] int target ids.
        chunk: sequence positions per scan step (per-step logits live
            memory is B·chunk·V floats).
        mask: optional [B, L] 0/1 validity; masked positions contribute
            neither loss nor count.

    Returns the scalar mean CE over valid positions — identical numerics to
    ``optax.softmax_cross_entropy_with_integer_labels`` over full logits
    (fp32 accumulation), tested in tests/test_models.py.
    """
    import optax

    B, L, H = hidden.shape
    if mask is None:
        mask = jnp.ones((B, L), jnp.float32)
    mask = mask.astype(jnp.float32)
    chunk = max(1, min(int(chunk), L))
    pad = (-L) % chunk
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    nc = (L + pad) // chunk
    hs = hidden.reshape(B, nc, chunk, H).transpose(1, 0, 2, 3)
    ts = targets.reshape(B, nc, chunk).transpose(1, 0, 2)
    ms = mask.reshape(B, nc, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def body(carry, xs):
        h_c, t_c, m_c = xs
        # keep the GEMM in the inputs' dtype (bf16 multiply / fp32
        # accumulate on the MXU) — an explicit fp32 upcast would run the
        # hot matmul as full fp32, several times slower on TPU for no
        # accuracy gain over fp32 accumulation
        logits = jnp.einsum(
            "bch,vh->bcv", h_c, emb,
            preferred_element_type=jnp.float32,
        )
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, t_c)
        tot, cnt = carry
        return (tot + jnp.sum(ce * m_c), cnt + jnp.sum(m_c)), None

    (tot, cnt), _ = lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)), (hs, ts, ms)
    )
    return tot / jnp.maximum(cnt, 1.0)


def chunked_causal_lm_loss(out, input_ids, mask=None, *, chunk: int = 128):
    """Next-token CE for ``GPT(chunked_head=True)`` outputs.

    ``out`` is the model's ``(hidden, embedding)`` pair; semantics match
    ``models.gpt.causal_lm_loss`` on full logits (predict t+1 from ≤ t,
    optional [B, L] padding mask) without materializing them.
    """
    hidden, emb = out
    targets = input_ids[:, 1:]
    hidden = hidden[:, :-1]
    m = None if mask is None else mask[:, 1:]
    return chunked_softmax_cross_entropy(
        hidden, emb, targets, chunk=chunk, mask=m
    )

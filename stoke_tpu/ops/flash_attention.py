"""Pallas flash attention for TPU (forward + custom-VJP backward).

The dense-attention hot path materializes the [L, L] score matrix in HBM;
this kernel keeps score blocks in VMEM and streams K/V blocks through the
MXU with the online-softmax recurrence, so attention memory is O(L·D) and
the score traffic never leaves the chip (pallas_guide.md: HBM→VMEM→MXU).

Layout: q/k/v are [BH, L, D] (batch×heads flattened outside).  The grid is
(BH, q_blocks, k_blocks) with the k dimension innermost — on TPU the grid is
executed sequentially per core, so VMEM scratch (the running max ``m``,
normalizer ``l``, and output accumulator) persists across the k sweep of one
q block (initialized at k==0, finalized at the last k).

Backward implements the standard flash recurrence from the saved
logsumexp rows: two kernels, one accumulating dQ over the k sweep and one
accumulating dK/dV over the q sweep, both recomputing P blocks on-chip.

Supports causal masking (upper-triangle k blocks are skipped entirely, not
just masked) and a [B, L] key-padding mask.  ``interpret=True`` runs the
same kernels through the pallas interpreter (used for CPU tests).

Used via ``make_flash_attention()`` as a drop-in ``attention_fn`` for
``stoke_tpu.models.bert`` — composable with the ring transform (ring for
cross-device sequence sharding, flash for the on-chip block math).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: block-size candidates, best first — on v5e the 512x512 blocking is ~3.5x
#: faster than 128x128 (K/V HBM refetch traffic scales as L^2·D/block_q;
#: measured sweep in scripts/flash_tpu_check.py / BENCH_NOTES.md)
_BLOCK_CANDIDATES = (512, 256, 128, 64)
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30


def _pick_block(requested: Optional[int], L: int, default: int) -> int:
    """Resolve a block size: explicit request wins (clamped to L); when
    L ≤ default a single full-length block is used (always legal, one grid
    step); otherwise the largest candidate ≤ default dividing L."""
    if requested is not None:
        return min(requested, L)
    if L <= default:
        return L
    for c in _BLOCK_CANDIDATES:
        if c <= default and L % c == 0:
            return c
    raise ValueError(
        f"flash attention auto block selection: no candidate in "
        f"{_BLOCK_CANDIDATES} divides sequence length {L}. Pad the sequence "
        f"to a multiple of one of the candidates (e.g. {64 * -(-L // 64)}), "
        f"or pass an explicit block size that divides L."
    )


# --------------------------------------------------------------------------- #
# forward
# --------------------------------------------------------------------------- #


def _fwd_kernel(mask_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc, m_sc, l_sc, *, scale, causal, block_q, block_k, L):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    qi = pl.program_id(1)
    run = True
    if causal:
        # a k block strictly above the diagonal contributes nothing
        run = qi * block_q + block_q - 1 >= ki * block_k

    @pl.when(run)
    def _block():
        q = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        if mask_ref is not None:
            valid = mask_ref[0] > 0  # [1, block_k] row, broadcasts over q
            s = jnp.where(valid, s, _NEG_INF)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_prev = m_sc[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(s > _NEG_INF * 0.5, p, 0.0)
        corr = jnp.exp(m_prev - m_new)  # [block_q, 1]
        l_sc[:, 0:1] = l_sc[:, 0:1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_sc[:, 0:1] = m_new
        pv = jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc[:] = acc[:] * corr + pv

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_sc[:, 0:1]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc[:] / safe_l).astype(o_ref.dtype)
        # logsumexp rows for the backward pass; fully-masked rows get -inf.
        # lse is laid out [BH, L, 1] (column blocks) so the block shape
        # (1, block_q, 1) satisfies the Mosaic (8, 128)-or-full tiling rule
        # and the backward kernels read it as the [block_q, 1] column they
        # subtract from score blocks — no relayout on either side.
        lse = m_sc[:, 0:1] + jnp.log(safe_l)
        lse_ref[0] = jnp.where(l > 0, lse, _NEG_INF)


def _flash_forward(q, k, v, mask, heads, scale, causal, block_q, block_k,
                   interpret):
    BH, L, D = q.shape
    nq, nk = pl.cdiv(L, block_q), pl.cdiv(L, block_k)
    kernel = functools.partial(
        _fwd_kernel if mask is not None else
        functools.partial(_fwd_kernel, None),
        scale=scale, causal=causal, block_q=block_q, block_k=block_k, L=L,
    )
    in_specs = []
    args = []
    if mask is not None:
        # mask is [B, 1, L]: the length-1 middle axis makes the (1, 1, block_k)
        # block legal under the Mosaic tiling rule (see lse layout note)
        in_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda bh, qi, ki: (bh // heads, 0, ki))
        )
        args.append(mask)
    in_specs += [
        pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
    ]
    args += [q, k, v]
    out, lse = pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, L, D), q.dtype),
            jax.ShapeDtypeStruct((BH, L, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out, lse


# --------------------------------------------------------------------------- #
# backward
# --------------------------------------------------------------------------- #


def _recompute_p(q_ref, k_ref, lse_col, mask_ref, qi, ki, *, scale, causal,
                 block_q, block_k):
    """Recompute the softmax block P from saved logsumexp rows.

    ``lse_col`` is the [block_q, 1] column slice of the [BH, L, 1] lse;
    ``mask_ref`` blocks are [1, 1, block_k] rows — both broadcast against
    the [block_q, block_k] score block without any relayout."""
    q = q_ref[0].astype(jnp.float32)
    kb = k_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if mask_ref is not None:
        s = jnp.where(mask_ref[0] > 0, s, _NEG_INF)
    if causal:
        qpos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        kpos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(qpos >= kpos, s, _NEG_INF)
    p = jnp.exp(s - lse_col)
    return jnp.where(s > _NEG_INF * 0.5, p, 0.0)


def _dq_kernel(mask_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_acc, *, scale, causal, block_q, block_k):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = qi * block_q + block_q - 1 >= ki * block_k

    @pl.when(run)
    def _block():
        p = _recompute_p(
            q_ref, k_ref, lse_ref[0], mask_ref, qi, ki,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0])
        dq_acc[:] += scale * jax.lax.dot_general(
            ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(mask_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, block_q,
                block_k):
    qi = pl.program_id(2)  # innermost: sweep over q blocks
    nq = pl.num_programs(2)
    ki = pl.program_id(1)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = qi * block_q + block_q - 1 >= ki * block_k

    @pl.when(run)
    def _block():
        p = _recompute_p(
            q_ref, k_ref, lse_ref[0], mask_ref, qi, ki,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        do = do_ref[0].astype(jnp.float32)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0])
        dk_acc[:] += scale * jax.lax.dot_general(
            ds, q_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(res, g, heads, scale, causal, block_q, block_k, interpret,
                    dlse=None):
    q, k, v, mask, out, lse = res
    do = g
    BH, L, D = q.shape
    nq, nk = pl.cdiv(L, block_q), pl.cdiv(L, block_k)
    # delta_i = rowsum(dO_i * O_i), stored [BH, L, 1] like lse
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )
    if dlse is not None:
        # when lse is itself an output (ring-attention hop composition), its
        # cotangent folds into the same kernels: d lse_i/d s_ij = p_ij, so
        # ds = p*(dp - delta + dlse) = p*(dp - (delta - dlse))
        delta = delta - dlse.astype(jnp.float32)

    def specs(maskless_first, grid_inner_is_k):
        idx_q = (lambda bh, a, b: (bh, a, 0)) if grid_inner_is_k else (
            lambda bh, a, b: (bh, b, 0))
        idx_k = (lambda bh, a, b: (bh, b, 0)) if grid_inner_is_k else (
            lambda bh, a, b: (bh, a, 0))
        sp = []
        if mask is not None:
            sp.append(pl.BlockSpec((1, 1, block_k), lambda bh, a, b: (
                bh // heads, 0, b if grid_inner_is_k else a)))
        sp += [
            pl.BlockSpec((1, block_q, D), idx_q),   # q
            pl.BlockSpec((1, block_k, D), idx_k),   # k
            pl.BlockSpec((1, block_k, D), idx_k),   # v
            pl.BlockSpec((1, block_q, D), idx_q),   # do
            pl.BlockSpec((1, block_q, 1), idx_q),   # lse [BH, L, 1]
            pl.BlockSpec((1, block_q, 1), idx_q),   # delta [BH, L, 1]
        ]
        return sp

    args = ([mask] if mask is not None else []) + [q, k, v, do, lse, delta]

    dq_kernel = functools.partial(
        _dq_kernel if mask is not None else functools.partial(_dq_kernel, None),
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(BH, nq, nk),
        in_specs=specs(mask is None, grid_inner_is_k=True),
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, L, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(*args)

    dkv_kernel = functools.partial(
        _dkv_kernel if mask is not None else functools.partial(_dkv_kernel, None),
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(BH, nk, nq),
        in_specs=specs(mask is None, grid_inner_is_k=False),
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, L, D), k.dtype),
            jax.ShapeDtypeStruct((BH, L, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return dq, dk, dv, None


# --------------------------------------------------------------------------- #
# public API
# --------------------------------------------------------------------------- #


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9)
)
def _flash(q, k, v, mask, heads, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_forward(
        q, k, v, mask, heads, scale, causal, block_q, block_k, interpret
    )
    return out


def _flash_fwd_rule(q, k, v, mask, heads, scale, causal, block_q, block_k,
                    interpret):
    out, lse = _flash_forward(
        q, k, v, mask, heads, scale, causal, block_q, block_k, interpret
    )
    return out, (q, k, v, mask, out, lse)


def _flash_bwd_rule(heads, scale, causal, block_q, block_k, interpret, res, g):
    return _flash_backward(
        res, g, heads, scale, causal, block_q, block_k, interpret
    )


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9)
)
def _flash_with_lse(q, k, v, mask, heads, scale, causal, block_q, block_k,
                    interpret):
    """Like ``_flash`` but also returns the [BH, L, 1] logsumexp rows —
    the composition hook for ring attention (hop outputs are re-weighted by
    their lse, so lse needs a real gradient path)."""
    return _flash_forward(
        q, k, v, mask, heads, scale, causal, block_q, block_k, interpret
    )


def _flash_lse_fwd_rule(q, k, v, mask, heads, scale, causal, block_q, block_k,
                        interpret):
    out, lse = _flash_forward(
        q, k, v, mask, heads, scale, causal, block_q, block_k, interpret
    )
    return (out, lse), (q, k, v, mask, out, lse)


def _flash_lse_bwd_rule(heads, scale, causal, block_q, block_k, interpret,
                        res, g):
    do, dlse = g
    return _flash_backward(
        res, do, heads, scale, causal, block_q, block_k, interpret, dlse=dlse
    )


_flash_with_lse.defvjp(_flash_lse_fwd_rule, _flash_lse_bwd_rule)


def flash_attention(
    q, k, v, mask=None, *, causal: bool = False,
    block_q: Optional[int] = None, block_k: Optional[int] = None,
    interpret: Optional[bool] = None, return_lse: bool = False,
):
    """Flash attention on [B, H, L, D] inputs with optional [B, L] key mask.

    ``return_lse=True`` additionally returns the [B, H, L] logsumexp rows
    (fully-masked rows get the ``_NEG_INF`` sentinel) — used by ring
    attention to merge per-hop partial attentions; gradients flow through
    both outputs.

    ``interpret=None`` auto-selects the pallas interpreter off-TPU (tests).
    ``block_q``/``block_k=None`` auto-selects the largest block in
    ``_BLOCK_CANDIDATES`` that divides L (bigger q blocks cut the K/V HBM
    refetch factor — the measured optimum on v5e is 512x512).  L must be
    divisible by the resolved block sizes.
    """
    if q.ndim != 4:
        raise ValueError(f"expected [B, H, L, D] inputs, got {q.shape}")
    if q.shape != k.shape or k.shape != v.shape:
        raise ValueError(
            f"q/k/v shapes must match, got {q.shape}/{k.shape}/{v.shape}"
        )
    B, H, L, D = q.shape
    if mask is not None and mask.shape != (B, L):
        raise ValueError(f"mask must be [B, L] = {(B, L)}, got {mask.shape}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = _pick_block(block_q, L, DEFAULT_BLOCK_Q)
    block_k = _pick_block(block_k, L, DEFAULT_BLOCK_K)
    if L % block_q or L % block_k:
        raise ValueError(
            f"sequence length {L} must be divisible by block sizes "
            f"({block_q}, {block_k})"
        )
    flat = lambda t: t.reshape(B * H, L, D)
    # [B, 1, L]: the unit middle axis keeps every mask block legal under the
    # Mosaic (8, 128)-or-full tiling rule (see the lse layout note in
    # _fwd_kernel)
    mask3 = None if mask is None else mask.reshape(B, 1, L)
    if return_lse:
        out, lse = _flash_with_lse(
            flat(q), flat(k), flat(v), mask3, H, 1.0 / (D**0.5), causal,
            block_q, block_k, interpret,
        )
        return out.reshape(B, H, L, D), lse.reshape(B, H, L)
    out = _flash(
        flat(q), flat(k), flat(v), mask3, H, 1.0 / (D**0.5), causal,
        block_q, block_k, interpret,
    )
    return out.reshape(B, H, L, D)


#: numerics-contract tolerances for validating the kernel against the dense
#: reference at bf16 inputs (shared by tests/test_flash_tpu.py and
#: scripts/flash_tpu_check.py so the pytest gate and the standalone on-TPU
#: check can never disagree)
FWD_ATOL_BF16 = 2e-2
BWD_RTOL_BF16 = 0.05


def dense_reference(q, k, v, mask=None, causal=False):
    """O(L²) dense attention in fp32 — the ground truth the flash kernel is
    validated against ([B, H, L, D] inputs, optional [B, L] key mask)."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / (q.shape[-1] ** 0.5)
    L = q.shape[2]
    if mask is not None:
        s = jnp.where(mask[:, None, None, :] > 0, s, _NEG_INF)
    if causal:
        qpos = jax.lax.broadcasted_iota(jnp.int32, (L, L), 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (L, L), 1)
        s = jnp.where((qpos >= kpos)[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def paged_decode_attention(q, k_pages, v_pages, block_tables, context_lens):
    """Decode-mode attention over a paged KV-cache (ISSUE 9 serving path).

    Single-token decode is HBM-bandwidth-bound, not MXU-bound: each query
    attends over its own sequence's cached K/V, which lives scattered
    across a block pool addressed by a per-request block table (the
    vLLM-style layout, sized so freed blocks refill mid-flight —
    ``stoke_tpu.serving.kv_cache``).  The kernel gathers each request's
    blocks from the pool and runs the same fp32 masked softmax the dense
    reference uses — the flash recurrence degenerates at q-length 1 (one
    online-softmax row), so the gather IS the whole memory schedule and
    XLA lowers it to per-block dynamic slices out of HBM
    (pallas_guide.md: KV caches live in HBM; a dedicated Pallas decode
    kernel streaming blocks through VMEM is the TPU follow-up, the math
    below is its reference semantics).

    Args:
        q: ``[B, H, 1, D]`` current-token queries (one per decode slot).
        k_pages / v_pages: ``[NB, BS, H, D]`` block pool for ONE layer
            (NB blocks of BS tokens).
        block_tables: ``[B, MAX_BLOCKS] int32`` — each slot's block ids
            into the pool, in sequence order; unused entries may point
            anywhere (the reserved scratch block 0 by convention) — they
            are masked by ``context_lens``.
        context_lens: ``[B] int32`` — valid tokens per slot INCLUDING the
            current one (positions ``>= context_lens[b]`` are masked).

    Returns ``[B, H, 1, D]`` attention outputs in the query dtype.
    """
    B, H, one, D = q.shape
    if one != 1:
        raise ValueError(
            f"paged_decode_attention is single-token decode; got q-length "
            f"{one} (prefill goes through flash_attention/dense_attention)"
        )
    NB, BS = k_pages.shape[0], k_pages.shape[1]
    # gather each slot's window: [B, MAX_BLOCKS, BS, H, D] -> [B, W, H, D]
    k = jnp.take(k_pages, block_tables, axis=0).reshape(B, -1, H, D)
    v = jnp.take(v_pages, block_tables, axis=0).reshape(B, -1, H, D)
    s = jnp.einsum(
        "bhqd,bwhd->bhqw", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / (D**0.5)
    w_pos = jnp.arange(k.shape[1], dtype=jnp.int32)
    valid = w_pos[None, :] < context_lens[:, None]  # [B, W]
    s = jnp.where(valid[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqw,bwhd->bhqd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


#: default number of KV pages streamed HBM→VMEM per kernel step (ISSUE 13)
#: — bigger groups amortize DMA issue overhead and enlarge the per-step
#: matmul; both decode knobs live in the autotune catalog
#: (``stoke_tpu.autotune.KNOB_KIND``) so ``scripts/autotune.py --workload
#: serve_decode`` can sweep them on-chip
DEFAULT_DECODE_PAGES_PER_BLOCK = 8
#: default heads fetched per kernel step (each head owns its own K/V slice,
#: so blocking heads widens the DMA transfers rather than sharing them)
DEFAULT_DECODE_BLOCK_H = 1


def _pick_divisor(requested: Optional[int], total: int, default: int) -> int:
    """Largest divisor of ``total`` that is <= the requested (or default)
    value — decode block knobs must tile their dimension exactly, and a
    sweep-supplied candidate that does not divide degrades to the nearest
    legal size instead of failing the trial."""
    want = default if requested is None else int(requested)
    want = max(1, min(want, total))
    while total % want:
        want -= 1
    return want


def _paged_decode_kernel(tables_ref, lens_ref, q_ref, k_hbm, v_hbm, o_ref,
                         k_vmem, v_vmem, sem_k, sem_v, *, block_size,
                         pages_per_block, n_steps, block_h, scale):
    """Streaming paged-decode attention body (one (batch, head-group) grid
    cell).  K/V pages stay in HBM (``pltpu.ANY``); each step DMAs
    ``pages_per_block`` pages of the request's block table into a
    double-buffered VMEM landing zone (the fetch for step j+1 is issued
    before step j's compute — pallas_guide.md double-buffering pattern) and
    folds them into the fp32 online-softmax accumulators.  Inactive table
    entries point at the reserved scratch block 0, so every DMA is legal;
    their positions are masked by ``context_lens``, so they contribute
    nothing (the same dead-block traffic the jnp reference gather pays)."""
    b = pl.program_id(0)
    hg = pl.program_id(1)
    ctx = lens_ref[b, 0]
    group = pages_per_block * block_size

    def copies(j, slot):
        # one descriptor per (page, plane): start() issues them, wait()
        # rebuilds the SAME descriptors so the semaphore byte accounting
        # matches exactly
        out = []
        for p in range(pages_per_block):
            blk = tables_ref[b, j * pages_per_block + p]
            for src, dst, sem in (
                (k_hbm, k_vmem, sem_k), (v_hbm, v_vmem, sem_v)
            ):
                out.append(
                    pltpu.make_async_copy(
                        src.at[blk, :, pl.ds(hg * block_h, block_h), :],
                        dst.at[slot, pl.ds(p * block_size, block_size)],
                        sem.at[slot],
                    )
                )
        return out

    D = q_ref.shape[-1]
    q = q_ref[0, :, 0, :].astype(jnp.float32) * scale  # [block_h, D]
    m = [jnp.full((1, 1), _NEG_INF, jnp.float32) for _ in range(block_h)]
    l = [jnp.zeros((1, 1), jnp.float32) for _ in range(block_h)]
    acc = [jnp.zeros((1, D), jnp.float32) for _ in range(block_h)]

    for c in copies(0, 0):
        c.start()
    for j in range(n_steps):
        slot = j % 2
        if j + 1 < n_steps:
            for c in copies(j + 1, (j + 1) % 2):
                c.start()
        for c in copies(j, slot):
            c.wait()
        kb = k_vmem[slot].astype(jnp.float32)  # [group, block_h, D]
        vb = v_vmem[slot].astype(jnp.float32)
        pos = j * group + jax.lax.broadcasted_iota(
            jnp.int32, (1, group), 1
        )
        valid = pos < ctx  # [1, group]
        for hh in range(block_h):
            s = jax.lax.dot_general(
                q[hh : hh + 1], kb[:, hh, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [1, group]
            s = jnp.where(valid, s, _NEG_INF)
            m_new = jnp.maximum(m[hh], jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            p = jnp.where(s > _NEG_INF * 0.5, p, 0.0)
            corr = jnp.exp(m[hh] - m_new)
            l[hh] = l[hh] * corr + jnp.sum(p, axis=-1, keepdims=True)
            m[hh] = m_new
            pv = jax.lax.dot_general(
                p, vb[:, hh, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc[hh] = acc[hh] * corr + pv

    for hh in range(block_h):
        safe_l = jnp.where(l[hh] > 0, l[hh], 1.0)
        o_ref[0, hh] = (acc[hh] / safe_l).astype(o_ref.dtype)


def paged_decode_attention_pallas(
    q, k_pages, v_pages, block_tables, context_lens, *,
    pages_per_block: Optional[int] = None, block_h: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Pallas paged-decode attention: the dedicated streaming kernel for
    the serve fast path (ISSUE 13), with
    :func:`paged_decode_attention` as its pinned reference semantics.

    Decode attention is HBM-bandwidth-bound: the whole job is moving each
    request's cached K/V past the VPU once.  The jnp reference leaves the
    memory schedule to XLA's gather lowering; this kernel owns it — grid
    over ``(batch, heads/block_h)``, the per-request block table in SMEM,
    the page pool left in HBM (``pltpu.ANY``), and each grid cell walking
    its table ``pages_per_block`` pages at a time through a
    double-buffered VMEM landing buffer (``make_async_copy`` issue for
    step j+1 before step j's compute) into the fp32 online-softmax
    accumulation.  Same contract as the reference: positions >=
    ``context_lens[b]`` are masked, unused table entries point at the
    reserved scratch block 0 (their DMA is legal, their contribution
    masked), output in the query dtype.

    Args mirror :func:`paged_decode_attention`; the extra knobs:

    Args:
        pages_per_block: KV pages fetched per kernel step (clamped to the
            largest divisor of the table width; default
            ``DEFAULT_DECODE_PAGES_PER_BLOCK``).  The autotune catalog
            knob ``decode_pages_per_block``.
        block_h: heads per grid cell (clamped to a divisor of H; default
            ``DEFAULT_DECODE_BLOCK_H``) — widens each DMA by fetching
            several heads' slices per page.  Catalog knob
            ``decode_block_h``.
        interpret: run through the pallas interpreter (``None`` =
            auto-select off-TPU, like :func:`flash_attention` — the CPU
            parity mode the tests pin against the reference).
    """
    B, H, one, D = q.shape
    if one != 1:
        raise ValueError(
            f"paged_decode_attention_pallas is single-token decode; got "
            f"q-length {one}"
        )
    if k_pages.shape != v_pages.shape or k_pages.ndim != 4:
        raise ValueError(
            f"k_pages/v_pages must be identical [NB, BS, H, D] pools, got "
            f"{k_pages.shape}/{v_pages.shape}"
        )
    if k_pages.shape[2] != H or k_pages.shape[3] != D:
        raise ValueError(
            f"page pool heads/dim {k_pages.shape[2:]} do not match the "
            f"query's {(H, D)}"
        )
    if block_tables.ndim != 2 or block_tables.shape[0] != B:
        raise ValueError(
            f"block_tables must be [B={B}, MAX_BLOCKS], got "
            f"{block_tables.shape}"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    BS = int(k_pages.shape[1])
    MB = int(block_tables.shape[1])
    ppb = _pick_divisor(pages_per_block, MB, DEFAULT_DECODE_PAGES_PER_BLOCK)
    bh = _pick_divisor(block_h, H, DEFAULT_DECODE_BLOCK_H)
    n_steps = MB // ppb
    kernel = functools.partial(
        _paged_decode_kernel,
        block_size=BS, pages_per_block=ppb, n_steps=n_steps, block_h=bh,
        scale=1.0 / (D**0.5),
    )
    out = pl.pallas_call(
        kernel,
        grid=(B, H // bh),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # block tables [B, MB]
            pl.BlockSpec(memory_space=pltpu.SMEM),  # context lens [B, 1]
            pl.BlockSpec((1, bh, 1, D), lambda b, h: (b, h, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # K pool stays in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # V pool stays in HBM
        ],
        out_specs=pl.BlockSpec((1, bh, 1, D), lambda b, h: (b, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, 1, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, ppb * BS, bh, D), k_pages.dtype),
            pltpu.VMEM((2, ppb * BS, bh, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        context_lens.reshape(B, 1).astype(jnp.int32),
        q,
        k_pages,
        v_pages,
    )
    return out


def paged_prefill_chunk_attention(q, k_pages, v_pages, block_tables,
                                  positions):
    """Chunked-prefill attention over a paged KV-cache (ISSUE 13).

    A prompt chunk's queries attend over everything already cached for the
    request — the earlier chunks' K/V (written to the block pool by prior
    chunk dispatches) plus this chunk's own (written by the hook before
    attention runs, exactly like decode writes the fresh token first).
    Causality is positional: query at global position ``p`` attends cache
    window positions ``<= p``, which covers both the intra-chunk causal
    mask and the inter-chunk prefix in one predicate.  The generalization
    of :func:`paged_decode_attention` to q-length C (its C == 1, positions
    == context_lens - 1 special case) and the reference semantics for a
    future Pallas chunk kernel.

    Args:
        q: ``[B, H, C, D]`` chunk queries.
        k_pages / v_pages: ``[NB, BS, H, D]`` block pool for one layer.
        block_tables: ``[B, MAX_BLOCKS] int32`` per-request block ids.
        positions: ``[B, C] int32`` global token positions of the chunk's
            queries (padding rows past the prompt end may hold clamped
            positions — their outputs are discarded by the caller).

    Returns ``[B, H, C, D]`` attention outputs in the query dtype.
    """
    B, H, C, D = q.shape
    k = jnp.take(k_pages, block_tables, axis=0).reshape(B, -1, H, D)
    v = jnp.take(v_pages, block_tables, axis=0).reshape(B, -1, H, D)
    s = jnp.einsum(
        "bhqd,bwhd->bhqw", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / (D**0.5)
    w_pos = jnp.arange(k.shape[1], dtype=jnp.int32)
    valid = w_pos[None, None, :] <= positions[:, :, None]  # [B, C, W]
    s = jnp.where(valid[:, None, :, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqw,bwhd->bhqd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


#: default KV pages streamed per step by the speculative verify kernel
#: (ISSUE 17) — its own autotune catalog knob (``verify_pages_per_block``)
#: because the verify grid amortizes each fetched page over k+1 query rows,
#: shifting the DMA/compute balance away from the decode kernel's optimum
DEFAULT_VERIFY_PAGES_PER_BLOCK = 8
#: default heads per verify grid cell (catalog knob ``verify_block_h``)
DEFAULT_VERIFY_BLOCK_H = 1


def paged_verify_attention(q, k_pages, v_pages, block_tables, positions):
    """Speculative-verify attention over a paged KV-cache (ISSUE 17).

    The verify program scores a request's next token plus its k draft
    continuations in ONE dispatch: S = k+1 query rows per request, each
    attending the cache window at its own global position.  Semantically
    this IS :func:`paged_prefill_chunk_attention` — multi-token queries
    over the paged prefix with the positional causal predicate — applied
    at decode time, which is exactly why the chunk program shape pins the
    verify semantics (ROADMAP item 2).  Kept as its own named entry point
    so the Pallas fast path (:func:`paged_verify_attention_pallas`) has
    pinned reference semantics independent of future chunk changes.

    Args:
        q: ``[B, H, S, D]`` verify queries (S = speculative_k + 1).
        k_pages / v_pages: ``[NB, BS, H, D]`` block pool for one layer.
        block_tables: ``[B, MAX_BLOCKS] int32`` per-request block ids.
        positions: ``[B, S] int32`` global positions of the verify
            queries; padding rows (requests with short drafts) carry
            clamped positions and their outputs are discarded.

    Returns ``[B, H, S, D]`` attention outputs in the query dtype.
    """
    return paged_prefill_chunk_attention(
        q, k_pages, v_pages, block_tables, positions
    )


def _paged_verify_kernel(tables_ref, pos_ref, q_ref, k_hbm, v_hbm, o_ref,
                         k_vmem, v_vmem, sem_k, sem_v, *, block_size,
                         pages_per_block, n_steps, block_h, n_q, scale):
    """Streaming verify-attention body: the :func:`_paged_decode_kernel`
    schedule (double-buffered HBM→VMEM page DMA, fp32 online softmax)
    generalized to ``n_q`` query rows per request.  Each fetched page is
    folded into ALL n_q rows' accumulators — the per-byte compute that
    makes speculative decode pay: one table walk now scores k+1
    candidate positions instead of one."""
    b = pl.program_id(0)
    hg = pl.program_id(1)
    group = pages_per_block * block_size

    def copies(j, slot):
        out = []
        for p in range(pages_per_block):
            blk = tables_ref[b, j * pages_per_block + p]
            for src, dst, sem in (
                (k_hbm, k_vmem, sem_k), (v_hbm, v_vmem, sem_v)
            ):
                out.append(
                    pltpu.make_async_copy(
                        src.at[blk, :, pl.ds(hg * block_h, block_h), :],
                        dst.at[slot, pl.ds(p * block_size, block_size)],
                        sem.at[slot],
                    )
                )
        return out

    D = q_ref.shape[-1]
    q = q_ref[0].astype(jnp.float32) * scale  # [block_h, n_q, D]
    qpos = jnp.stack(
        [pos_ref[b, s] for s in range(n_q)]
    ).reshape(n_q, 1)  # [n_q, 1] global positions out of SMEM
    m = [jnp.full((n_q, 1), _NEG_INF, jnp.float32) for _ in range(block_h)]
    l = [jnp.zeros((n_q, 1), jnp.float32) for _ in range(block_h)]
    acc = [jnp.zeros((n_q, D), jnp.float32) for _ in range(block_h)]

    for c in copies(0, 0):
        c.start()
    for j in range(n_steps):
        slot = j % 2
        if j + 1 < n_steps:
            for c in copies(j + 1, (j + 1) % 2):
                c.start()
        for c in copies(j, slot):
            c.wait()
        kb = k_vmem[slot].astype(jnp.float32)  # [group, block_h, D]
        vb = v_vmem[slot].astype(jnp.float32)
        pos = j * group + jax.lax.broadcasted_iota(
            jnp.int32, (1, group), 1
        )
        valid = pos <= qpos  # [n_q, group] positional causality
        for hh in range(block_h):
            s = jax.lax.dot_general(
                q[hh], kb[:, hh, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [n_q, group]
            s = jnp.where(valid, s, _NEG_INF)
            m_new = jnp.maximum(m[hh], jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            p = jnp.where(s > _NEG_INF * 0.5, p, 0.0)
            corr = jnp.exp(m[hh] - m_new)
            l[hh] = l[hh] * corr + jnp.sum(p, axis=-1, keepdims=True)
            m[hh] = m_new
            pv = jax.lax.dot_general(
                p, vb[:, hh, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc[hh] = acc[hh] * corr + pv

    for hh in range(block_h):
        safe_l = jnp.where(l[hh] > 0, l[hh], 1.0)
        o_ref[0, hh] = (acc[hh] / safe_l).astype(o_ref.dtype)


def paged_verify_attention_pallas(
    q, k_pages, v_pages, block_tables, positions, *,
    pages_per_block: Optional[int] = None, block_h: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Pallas verify attention: the k-token speculative-decode kernel
    (ISSUE 17), with :func:`paged_verify_attention` as its pinned
    reference semantics.

    Identical memory schedule to :func:`paged_decode_attention_pallas`
    — grid ``(batch, heads/block_h)``, block table in SMEM, page pools
    in HBM (``pltpu.ANY``), double-buffered VMEM landing zone — but each
    grid cell scores S = k+1 query rows against every streamed page, so
    the per-dispatch HBM traffic (the decode bottleneck) is amortized
    over up to k+1 emitted tokens.  Masking is positional per query row
    (``w_pos <= positions[b, s]``), matching the chunk-attention
    predicate rather than decode's ``< context_lens``.

    Args:
        q: ``[B, H, S, D]`` verify queries.
        k_pages / v_pages: ``[NB, BS, H, D]`` pools for one layer.
        block_tables: ``[B, MAX_BLOCKS] int32`` per-request block ids
            (unused entries at the reserved scratch block 0).
        positions: ``[B, S] int32`` per-query global positions.
        pages_per_block / block_h: catalog knobs
            ``verify_pages_per_block`` / ``verify_block_h`` (clamped to
            divisors like the decode kernel's).
        interpret: pallas interpreter toggle (``None`` = auto off-TPU).
    """
    B, H, S, D = q.shape
    if k_pages.shape != v_pages.shape or k_pages.ndim != 4:
        raise ValueError(
            f"k_pages/v_pages must be identical [NB, BS, H, D] pools, got "
            f"{k_pages.shape}/{v_pages.shape}"
        )
    if k_pages.shape[2] != H or k_pages.shape[3] != D:
        raise ValueError(
            f"page pool heads/dim {k_pages.shape[2:]} do not match the "
            f"query's {(H, D)}"
        )
    if block_tables.ndim != 2 or block_tables.shape[0] != B:
        raise ValueError(
            f"block_tables must be [B={B}, MAX_BLOCKS], got "
            f"{block_tables.shape}"
        )
    if positions.shape != (B, S):
        raise ValueError(
            f"positions must be [B={B}, S={S}], got {positions.shape}"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    BS = int(k_pages.shape[1])
    MB = int(block_tables.shape[1])
    ppb = _pick_divisor(pages_per_block, MB, DEFAULT_VERIFY_PAGES_PER_BLOCK)
    bh = _pick_divisor(block_h, H, DEFAULT_VERIFY_BLOCK_H)
    n_steps = MB // ppb
    kernel = functools.partial(
        _paged_verify_kernel,
        block_size=BS, pages_per_block=ppb, n_steps=n_steps, block_h=bh,
        n_q=S, scale=1.0 / (D**0.5),
    )
    out = pl.pallas_call(
        kernel,
        grid=(B, H // bh),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # block tables [B, MB]
            pl.BlockSpec(memory_space=pltpu.SMEM),  # positions [B, S]
            pl.BlockSpec((1, bh, S, D), lambda b, h: (b, h, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # K pool stays in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # V pool stays in HBM
        ],
        out_specs=pl.BlockSpec((1, bh, S, D), lambda b, h: (b, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, ppb * BS, bh, D), k_pages.dtype),
            pltpu.VMEM((2, ppb * BS, bh, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        positions.astype(jnp.int32),
        q,
        k_pages,
        v_pages,
    )
    return out


def make_flash_attention(
    causal: bool = False, block_q: Optional[int] = None,
    block_k: Optional[int] = None, interpret: Optional[bool] = None,
):
    """Build a flash ``attention_fn`` pluggable into
    ``BertEncoder(attention_fn=...)`` (same contract as ``dense_attention``)."""

    def attention_fn(q, k, v, bias, dropout_rng=None, dropout_rate=0.0,
                     deterministic=True):
        if dropout_rate > 0.0 and not deterministic:
            raise NotImplementedError(
                "flash attention does not support attention-prob dropout; "
                "set attention dropout to 0 (residual dropout is fine)"
            )
        mask = None
        if bias is not None:
            mask = (bias[:, 0, 0, :] > -1e8).astype(jnp.int32)
        return flash_attention(
            q, k, v, mask, causal=causal, block_q=block_q, block_k=block_k,
            interpret=interpret,
        )

    return attention_fn

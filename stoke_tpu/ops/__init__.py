"""TPU ops: sequence-parallel attention (ring / Ulysses) and future pallas
kernels.  The reference has NO model-level long-context support (SURVEY.md
§2.8: "no sequence/context parallelism, no ring attention, no Ulysses") —
only the data-level BucketedDistributedSampler; these ops are capability
upside of the TPU build, designed in from the start."""

from stoke_tpu.ops.attention import (
    inverse_permutation,
    make_ring_attention,
    make_ulysses_attention,
    make_zigzag_ring_attention,
    ring_attention,
    ulysses_attention,
    zigzag_permutation,
    zigzag_ring_attention,
)
from stoke_tpu.ops.chunked_ce import (
    chunked_causal_lm_loss,
    chunked_softmax_cross_entropy,
)
from stoke_tpu.ops.flash_attention import (
    flash_attention,
    make_flash_attention,
    paged_decode_attention,
    paged_decode_attention_pallas,
    paged_prefill_chunk_attention,
)

__all__ = [
    "paged_decode_attention",
    "paged_decode_attention_pallas",
    "paged_prefill_chunk_attention",
    "make_ring_attention",
    "make_ulysses_attention",
    "ring_attention",
    "ulysses_attention",
    "flash_attention",
    "make_flash_attention",
    "chunked_softmax_cross_entropy",
    "chunked_causal_lm_loss",
    "zigzag_ring_attention",
    "make_zigzag_ring_attention",
    "zigzag_permutation",
    "inverse_permutation",
]

"""Pipeline-parallel causal LM integrated with the Stoke facade.

Takes pipeline parallelism from building block (``parallel/pipeline.py``) to
a trainable model: a decoder-only LM whose transformer blocks are split into
S pipeline stages on a mesh ``stage`` axis, driven through the normal
``Stoke`` facade (any precision / clipping / accumulation / checkpointing
flags compose).

Parameter layout: ``{"embed": ..., "stages": <stage-stacked block tree>,
"head": ...}`` — stage-stacked leaves carry a leading [S, ...] dimension and
are placed on the stage axis with the variadic partition rule from
:func:`pipeline_parallel_rules` (("stage", ...)).  Embedding/head stay
replicated.  Gradients flow through the pipeline automatically (the ppermute
rotation is linear), so this is a fully trainable pipeline out of the box.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from stoke_tpu.engine import ModelAdapter
from stoke_tpu.models.bert import BERT_SIZES, BertSize, TransformerBlock
from stoke_tpu.parallel.pipeline import pipeline, stack_stage_params


def pipeline_parallel_rules(stage_axis: str = "stage") -> Tuple:
    """Partition rule placing stage-stacked parameters on the stage axis
    (for ``PartitionRulesConfig``): every leaf under ``stages/`` gets its
    leading dim sharded, remaining dims replicated (variadic ``...``)."""
    return ((r"^stages/", (stage_axis, "...")),)


class _StageBlock(nn.Module):
    """One pipeline stage: ``layers_per_stage`` causal transformer blocks."""

    size: BertSize
    layers_per_stage: int
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x):
        L = x.shape[1]
        causal = jnp.tril(jnp.ones((L, L), bool))
        bias = jnp.where(causal, 0.0, -1e9)[None, None, :, :].astype(x.dtype)
        for i in range(self.layers_per_stage):
            x = TransformerBlock(
                self.size.hidden, self.size.heads, self.size.ff,
                self.dropout_rate, name=f"block_{i}",
            )(x, bias, True)  # deterministic inside the pipeline
        return x


class PipelinedLM(ModelAdapter):
    """Decoder-only LM with pipeline-parallel blocks (ModelAdapter flavor).

    Args:
        mesh: mesh containing ``stage_axis`` (size S).
        vocab_size / size_name / max_len: as in :class:`~stoke_tpu.models.GPT`.
        num_microbatches: microbatches the input batch is split into (batch
            must be divisible); more microbatches = less pipeline bubble.
        layers_per_stage: blocks per stage (total layers = rounds × S × this).
        rounds: virtual stages per device (circular/interleaved schedule;
            bubble shrinks from (S-1)/(M+S-1) to (S-1)/(rounds·M+S-1)).
        remat: rematerialize each per-tick stage application (1F1B-style
            activation memory).
        data_axis: optional mesh axis for dp×pp composition — the batch dim
            of the microbatch stream shards over it (mesh must then carry
            both axes, e.g. ``MeshConfig(axes=("data", "stage"), ...)``).

    Usage:
        adapter = PipelinedLM(mesh, vocab_size=..., num_microbatches=4)
        variables = adapter.init(jax.random.PRNGKey(0))
        stoke = Stoke(model=adapter, params=variables, ...,
                      configs=[MeshConfig(...same axes...),
                               PartitionRulesConfig(
                                   rules=pipeline_parallel_rules())])
    """

    def __init__(
        self,
        mesh,
        vocab_size: int = 50257,
        size_name: str = "tiny",
        max_len: int = 256,
        num_microbatches: int = 2,
        layers_per_stage: Optional[int] = None,
        stage_axis: str = "stage",
        rounds: int = 1,
        remat: bool = False,
        data_axis: Optional[str] = None,
    ):
        self.mesh = mesh
        self.vocab_size = vocab_size
        self.size = BERT_SIZES[size_name]
        self.max_len = max_len
        self.num_microbatches = num_microbatches
        self.stage_axis = stage_axis
        self.rounds = int(rounds)
        self.num_stages = mesh.shape[stage_axis] * self.rounds
        if layers_per_stage is None:
            layers_per_stage = max(1, self.size.num_layers // self.num_stages)
        self.layers_per_stage = layers_per_stage
        self._stage_module = _StageBlock(self.size, layers_per_stage)
        self._piped = pipeline(
            lambda p, x: self._stage_module.apply({"params": p}, x),
            mesh, stage_axis, rounds=self.rounds, remat=remat,
            data_axis=data_axis,
        )

    # ------------------------------------------------------------------ #

    def init(self, rng) -> dict:
        """Host-side initialization of embed + S stage trees + head."""
        # local_devices, not devices: in a multi-process run the global
        # device list leads with process 0's devices, which other processes
        # cannot address (same fix as utils/init.py)
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            k_embed, k_pos, k_head, *k_stages = jax.random.split(
                rng, 3 + self.num_stages
            )
            H = self.size.hidden
            embed = {
                "tok": jax.random.normal(k_embed, (self.vocab_size, H)) * 0.02,
                "pos": jax.random.normal(k_pos, (self.max_len, H)) * 0.02,
            }
            dummy = jnp.zeros((1, 8, H), jnp.float32)
            stage_trees = [
                self._stage_module.init(k, dummy)["params"] for k in k_stages
            ]
            head = jax.random.normal(k_head, (H, self.vocab_size)) * 0.02
            return {
                "params": {
                    "embed": embed,
                    "stages": stack_stage_params(stage_trees),
                    "head": head,
                }
            }

    def _forward(self, params, input_ids):
        B, L = input_ids.shape
        M = self.num_microbatches
        if B % M != 0:
            raise ValueError(
                f"PipelinedLM: batch {B} not divisible by "
                f"num_microbatches={M}"
            )
        h = params["embed"]["tok"][input_ids] + params["embed"]["pos"][None, :L]
        h = h.reshape(M, B // M, L, -1)  # microbatch stream
        h = self._piped(params["stages"], h)
        h = h.reshape(B, L, -1)
        return h @ params["head"]

    def apply_train(self, variables, rng, args, kwargs):
        return self._forward(variables["params"], args[0]), {}

    def apply_eval(self, variables, args, kwargs):
        return self._forward(variables["params"], args[0])

"""Vision Transformer (ViT) classifier.

Rounds out the model library's vision side (CNNs: BasicNN/ResNet; this is
the transformer counterpart), reusing the shared transformer blocks so every
attention option (dense, pallas flash, ring/Ulysses) and partition-rule set
(tensor parallelism via the same qkv/ff rule paths) applies unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import flax.linen as nn
import jax.numpy as jnp

from stoke_tpu.models.bert import (
    BERT_SIZES,
    BertSize,
    TransformerBlock,
    dense_attention,
)


class ViT(nn.Module):
    """ViT classifier: patchify (conv stem) + CLS token + learned positions +
    transformer encoder + linear head.

    Args:
        size_name: width table entry ("tiny"…"large", shared with BERT).
        patch_size: square patch edge; image H/W must be divisible.
    """

    num_classes: int = 1000
    size_name: str = "tiny"
    patch_size: int = 4
    dropout_rate: float = 0.1
    attention_fn: Callable = dense_attention
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        size: BertSize = BERT_SIZES[self.size_name]
        B, H, W, C = x.shape
        if H % self.patch_size or W % self.patch_size:
            raise ValueError(
                f"ViT: image {H}x{W} not divisible by patch_size={self.patch_size}"
            )
        # patchify: one conv with stride = patch size (MXU-friendly)
        h = nn.Conv(
            size.hidden, (self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size), name="patch_embed",
        )(x)
        h = h.reshape(B, -1, size.hidden)  # [B, n_patches, hidden]
        cls = self.param(
            "cls_token", nn.initializers.normal(0.02), (1, 1, size.hidden)
        )
        h = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, size.hidden)), h], axis=1)
        n_tokens = h.shape[1]
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, n_tokens, size.hidden)
        )
        h = h + pos
        h = nn.Dropout(self.dropout_rate)(h, deterministic=not train)
        block = TransformerBlock
        if self.remat:
            block = nn.remat(TransformerBlock, static_argnums=(3,))
        for i in range(size.num_layers):
            h = block(
                size.hidden, size.heads, size.ff, self.dropout_rate,
                self.attention_fn, name=f"layer_{i}",
            )(h, None, not train)
        h = nn.LayerNorm(epsilon=1e-6, name="ln_final")(h)
        return nn.Dense(self.num_classes, name="head")(h[:, 0])


ViTTiny = partial(ViT, size_name="tiny")
ViTBase = partial(ViT, size_name="base", patch_size=16)

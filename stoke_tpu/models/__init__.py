"""Model library for examples/benchmarks.

The reference ships models only as example code (vendored torchvision ResNet,
examples/cifar10/model.py:19-293, and a BasicNN in README.md:100-102); here
they are first-class flax modules used by the examples, the benchmark, and
the driver entry point."""

from stoke_tpu.models.basic import BasicNN
from stoke_tpu.models.bert import (
    BERT_SIZES,
    BertBase,
    BertEncoder,
    BertForSequenceClassification,
    BertTiny,
    bert_tensor_parallel_rules,
    dense_attention,
)
from stoke_tpu.models.gpt import GPT, GPTBase, GPTTiny, causal_lm_loss

# The whole transformer family (BERT / GPT / ViT) shares TransformerBlock's
# parameter paths (attention/{qkv,out}, ff_{in,out}), so the Megatron-style
# column/row-parallel rules apply to every member; the aliases make intent
# explicit at call sites.
gpt_tensor_parallel_rules = bert_tensor_parallel_rules
vit_tensor_parallel_rules = bert_tensor_parallel_rules
from stoke_tpu.models.moe import (
    MoEFFN,
    MoETransformerBlock,
    moe_expert_parallel_rules,
)
from stoke_tpu.models.pipelined_lm import PipelinedLM, pipeline_parallel_rules
from stoke_tpu.models.vit import ViT, ViTBase, ViTTiny
from stoke_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)

__all__ = [
    "BasicNN",
    "BERT_SIZES",
    "BertBase",
    "BertEncoder",
    "BertForSequenceClassification",
    "BertTiny",
    "bert_tensor_parallel_rules",
    "gpt_tensor_parallel_rules",
    "vit_tensor_parallel_rules",
    "dense_attention",
    "GPT",
    "GPTBase",
    "GPTTiny",
    "causal_lm_loss",
    "MoEFFN",
    "MoETransformerBlock",
    "moe_expert_parallel_rules",
    "PipelinedLM",
    "pipeline_parallel_rules",
    "ViT",
    "ViTBase",
    "ViTTiny",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
]

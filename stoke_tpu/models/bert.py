"""BERT-style transformer encoder for sequence classification.

The reference's capability config #5 (BASELINE.md) is "BERT-base seq-cls with
BucketedDistributedSampler + grad-accum/clip"; the reference itself ships no
model code for it (stoke wraps user models).  This module provides the model
as a first-class flax implementation, TPU-native:

- NHWC-free: everything is [batch, seq, hidden] matmuls → MXU-friendly.
- Attention is pluggable (``attention_fn``) so the same encoder runs dense
  attention today and ring/flash attention (stoke_tpu.ops) for long context.
- Padding-aware: additive attention masks from an int mask, mean/CLS pooling.

Sizes follow the standard family table (base: 12 layers, hidden 768, 12
heads, ff 3072).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class BertSize:
    num_layers: int
    hidden: int
    heads: int
    ff: int


BERT_SIZES = {
    "tiny": BertSize(2, 128, 2, 512),
    "mini": BertSize(4, 256, 4, 1024),
    "small": BertSize(4, 512, 8, 2048),
    "medium": BertSize(8, 512, 8, 2048),
    "base": BertSize(12, 768, 12, 3072),
    "large": BertSize(24, 1024, 16, 4096),
}


def dense_attention(q, k, v, bias, dropout_rng=None, dropout_rate=0.0,
                    deterministic=True):
    """Standard softmax attention: q/k/v [B, H, L, D], bias broadcastable to
    [B, H, L, L].  The default ``attention_fn``; long-context variants
    (ring attention over a mesh seq axis) plug in with the same signature."""
    depth = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(depth).astype(q.dtype)
    if bias is not None:
        scores = scores + bias
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep / (1.0 - dropout_rate)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


class MultiHeadAttention(nn.Module):
    hidden: int
    heads: int
    dropout_rate: float = 0.1
    attention_fn: Callable = dense_attention

    @nn.compact
    def __call__(self, x, bias, deterministic: bool):
        B, L, H = x.shape
        head_dim = self.hidden // self.heads
        qkv = nn.DenseGeneral((3, self.heads, head_dim), name="qkv")(x)
        q, k, v = jnp.moveaxis(qkv, 2, 0)  # 3 × [B, L, heads, D]
        q, k, v = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))  # [B, H, L, D]
        rng = None
        if not deterministic and self.dropout_rate > 0.0:
            rng = self.make_rng("dropout")
        out = self.attention_fn(
            q, k, v, bias,
            dropout_rng=rng, dropout_rate=self.dropout_rate,
            deterministic=deterministic,
        )
        out = jnp.swapaxes(out, 1, 2).reshape(B, L, self.hidden)
        return nn.DenseGeneral(self.hidden, name="out")(out)


class TransformerBlock(nn.Module):
    hidden: int
    heads: int
    ff: int
    dropout_rate: float = 0.1
    attention_fn: Callable = dense_attention

    @nn.compact
    def __call__(self, x, bias, deterministic: bool):
        y = MultiHeadAttention(
            self.hidden, self.heads, self.dropout_rate, self.attention_fn,
            name="attention",
        )(x, bias, deterministic)
        y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=1e-12, name="ln_attn")(x + y)
        y = nn.Dense(self.ff, name="ff_in")(x)
        y = nn.gelu(y)
        y = nn.Dense(self.hidden, name="ff_out")(y)
        y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        return nn.LayerNorm(epsilon=1e-12, name="ln_ff")(x + y)


class BertEncoder(nn.Module):
    """Token + position + segment embeddings, N transformer blocks.

    ``layer_drop_rate`` enables progressive layer drop — stochastic depth
    with a linearly increasing drop probability over depth (layer i is kept
    with probability ``1 - rate * (i+1)/N`` during training).  This is the
    TPU-native counterpart of the reference's DeepSpeed PLD passthrough
    (configs.py:375-388, distributed.py:876-896); needs the ``layer_drop``
    rng stream (pass ``model_rng_keys=("dropout", "layer_drop")`` to Stoke).

    The reference PLD additionally exposes a theta/gamma TIME schedule
    (``DeepspeedPLDConfig``, configs.py:375-388): the global keep ratio
    warms from 1 toward ``theta`` as ``theta_bar(t) = (1-theta) *
    exp(-gamma*t) + theta``.  Set ``layer_drop_theta``/``layer_drop_gamma``
    and pass the current optimizer step as the ``global_step`` call kwarg
    (a traced scalar, so the scanned multi-step paths can feed a per-step
    value); the depth-linear drop fraction then becomes
    ``(1 - theta_bar(t)) * (i+1)/N``.  Without ``global_step`` (or with
    ``layer_drop_theta=None``) the static ``layer_drop_rate`` applies.
    """

    vocab_size: int
    size: BertSize
    max_len: int = 512
    dropout_rate: float = 0.1
    attention_fn: Callable = dense_attention
    remat: bool = False
    layer_drop_rate: float = 0.0
    layer_drop_theta: Optional[float] = None
    layer_drop_gamma: float = 0.001

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 train: bool = True, global_step=None):
        B, L = input_ids.shape
        h = nn.Embed(self.vocab_size, self.size.hidden, name="tok_emb")(input_ids)
        pos = jnp.arange(L)[None, :]
        h = h + nn.Embed(self.max_len, self.size.hidden, name="pos_emb")(pos)
        if token_type_ids is not None:
            h = h + nn.Embed(2, self.size.hidden, name="seg_emb")(token_type_ids)
        h = nn.LayerNorm(epsilon=1e-12, name="ln_emb")(h)
        h = nn.Dropout(self.dropout_rate)(h, deterministic=not train)
        if attention_mask is None:
            bias = None
        else:
            # additive mask: [B, 1, 1, L]; large negative on padding
            bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, -1e9).astype(
                h.dtype
            )
        block = TransformerBlock
        if self.remat:
            block = nn.remat(TransformerBlock, static_argnums=(3,))
        pld_on = train and (
            self.layer_drop_rate > 0.0 or self.layer_drop_theta is not None
        )
        drop_keys = None
        drop_frac = None
        if pld_on:
            drop_keys = jax.random.split(
                self.make_rng("layer_drop"), self.size.num_layers
            )
            if self.layer_drop_theta is not None and global_step is None:
                raise ValueError(
                    "Stoke -- layer_drop_theta is set (PLD theta/gamma time "
                    "schedule) but the forward was called without the "
                    "global_step kwarg; the schedule would silently never "
                    "engage.  Pass global_step=<optimizer step> (a traced "
                    "scalar), or use the static layer_drop_rate instead."
                )
            if self.layer_drop_theta is not None:
                # reference theta/gamma schedule (DeepspeedPLDConfig,
                # configs.py:375-388): keep ratio decays 1 -> theta
                theta = jnp.float32(self.layer_drop_theta)
                theta_bar = (1.0 - theta) * jnp.exp(
                    -jnp.float32(self.layer_drop_gamma)
                    * jnp.asarray(global_step, jnp.float32)
                ) + theta
                drop_frac = 1.0 - theta_bar
            else:
                drop_frac = jnp.float32(self.layer_drop_rate)
        for i in range(self.size.num_layers):
            h_new = block(
                self.size.hidden, self.size.heads, self.size.ff,
                self.dropout_rate, self.attention_fn, name=f"layer_{i}",
            )(h, bias, not train)
            if drop_keys is not None:
                keep_p = 1.0 - drop_frac * (i + 1) / self.size.num_layers
                keep = jax.random.bernoulli(drop_keys[i], keep_p)
                h = jnp.where(keep, h_new, h)
            else:
                h = h_new
        return h


class BertForSequenceClassification(nn.Module):
    """Encoder + tanh pooler over [CLS] + classifier head (BERT seq-cls)."""

    vocab_size: int = 30522
    num_classes: int = 2
    size_name: str = "base"
    max_len: int = 512
    dropout_rate: float = 0.1
    attention_fn: Callable = dense_attention
    remat: bool = False
    layer_drop_rate: float = 0.0
    layer_drop_theta: Optional[float] = None
    layer_drop_gamma: float = 0.001

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 train: bool = True, global_step=None):
        size = BERT_SIZES[self.size_name]
        h = BertEncoder(
            self.vocab_size, size, self.max_len, self.dropout_rate,
            self.attention_fn, self.remat, self.layer_drop_rate,
            self.layer_drop_theta, self.layer_drop_gamma,
            name="encoder",
        )(input_ids, attention_mask, token_type_ids, train, global_step)
        cls = nn.tanh(nn.Dense(size.hidden, name="pooler")(h[:, 0]))
        cls = nn.Dropout(self.dropout_rate)(cls, deterministic=not train)
        return nn.Dense(self.num_classes, name="classifier")(cls)


BertBase = partial(BertForSequenceClassification, size_name="base")
BertTiny = partial(BertForSequenceClassification, size_name="tiny")


def bert_tensor_parallel_rules(model_axis: str = "model"):
    """Megatron-style tensor-parallel partition rules for the BERT family
    (for ``PartitionRulesConfig``; requires a mesh with ``model_axis`` and
    heads/ff divisible by its size).

    Column-parallel: qkv projection (split over heads) and ff_in (split over
    the ff dim); row-parallel: attention output and ff_out (split over the
    input dim).  GSPMD derives the all-reduces after the row-parallel
    matmuls from these placements.
    """
    return (
        (r"attention/qkv/kernel", (None, None, model_axis, None)),
        (r"attention/qkv/bias", (None, model_axis, None)),
        (r"attention/out/kernel", (model_axis, None)),
        (r"ff_in/kernel", (None, model_axis)),
        (r"ff_in/bias", (model_axis,)),
        (r"ff_out/kernel", (model_axis, None)),
    )

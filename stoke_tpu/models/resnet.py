"""ResNet family (v1.5) in flax, TPU-native.

Replaces the reference's vendored torchvision ResNet
(examples/cifar10/model.py:19-293) with an idiomatic flax implementation:

- NHWC layout + channels-last convs (MXU-friendly; torch's NCHW is a CUDA
  idiom).
- BatchNorm via ``nn.BatchNorm`` with a ``batch_stats`` collection.  Under
  jit-GSPMD over a global batch the batch moments are computed over the
  LOGICALLY-GLOBAL batch, so cross-replica SyncBatchNorm (which the reference
  must convert to explicitly, distributed.py:575-579, :1318-1371) is the
  default behavior here.
- ``cifar_stem=True`` swaps the 7x7/stride-2+maxpool ImageNet stem for the
  3x3/stride-1 CIFAR stem (standard for 32x32 inputs).

Supports 18/34 (basic block) and 50/101/152 (bottleneck).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(
                residual
            )
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # zero-init the last BN scale so each block starts as identity
        # (standard ResNet v1.5 training recipe)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Configurable ResNet v1.5.

    Args:
        stage_sizes: blocks per stage, e.g. (3, 4, 6, 3) for ResNet-50.
        block: BasicBlock or BottleneckBlock.
        num_classes: classifier width.
        num_filters: stem width (64 for the standard family).
        cifar_stem: 3x3/s1 stem without maxpool (for 32x32 inputs).
        dtype: compute dtype of the module's intermediate activations; the
            framework's precision policy casts inputs/params, so the default
            float32 here means "inherit whatever comes in".
    """

    stage_sizes: Sequence[int]
    block: Callable
    num_classes: int = 1000
    num_filters: int = 64
    cifar_stem: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
        )
        if self.cifar_stem:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="norm_init")(x)
        x = nn.relu(x)
        if not self.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(self.stage_sizes):
            for b in range(n_blocks):
                strides = (2, 2) if stage > 0 and b == 0 else (1, 1)
                x = self.block(
                    filters=self.num_filters * 2**stage,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3), block=BottleneckBlock)

"""GPT-style decoder-only causal language model.

Rounds out the model library (the reference ships no models; SURVEY.md §2.7
has only the CIFAR example).  Reuses the transformer blocks from
``stoke_tpu.models.bert`` with causal attention; works with dense attention
(causal bias built in-model), the pallas flash kernel
(``make_flash_attention(causal=True)``), or sequence-parallel ring/Ulysses
(``make_ring_attention(..., causal=True)``) — set ``attention_is_causal``
when the attention_fn enforces causality itself.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from stoke_tpu.models.bert import (
    BERT_SIZES,
    BertSize,
    TransformerBlock,
    dense_attention,
)


class GPT(nn.Module):
    """Decoder-only LM: learned token+position embeddings, pre-LN-free
    (reuses the post-LN blocks), weight-tied LM head.

    Args:
        size_name: one of BERT_SIZES ("tiny"…"large") — decoder uses the
            same width table.
        attention_is_causal: True when ``attention_fn`` already applies the
            causal mask (flash/ring/ulysses built with ``causal=True``);
            False (default) builds an additive causal bias for dense
            attention.
        tie_embeddings: LM head = transpose of the token embedding.
    """

    vocab_size: int = 50257
    size_name: str = "tiny"
    max_len: int = 1024
    dropout_rate: float = 0.1
    attention_fn: Callable = dense_attention
    attention_is_causal: bool = False
    tie_embeddings: bool = True
    remat: bool = False
    # sparse-FFN option: replace the dense FFN with a switch MoE in every
    # `moe_every`-th block (0 experts = dense everywhere); shard experts
    # with moe_expert_parallel_rules() for expert parallelism
    moe_num_experts: int = 0
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_router_noise: float = 0.0  # needs the "router" rng stream when > 0
    moe_top_k: int = 1  # experts per token (1 = Switch, 2 = GShard-style)
    # return (hidden, embedding) instead of logits so the loss can run
    # chunked over the sequence (ops/chunked_ce.py) — the [B, L, V] logits
    # tensor is never materialized; requires tie_embeddings
    chunked_head: bool = False

    @nn.compact
    def __call__(self, input_ids, train: bool = True, positions=None,
                 decode: bool = False, kv_cache=None):
        """``positions`` (optional [L] or [B, L] int) overrides the default
        ``arange`` position ids — required when the sequence is laid out in
        a non-natural order (the zigzag layout of
        ``ops.zigzag_ring_attention``, packed sequences): the position
        embedding must follow each token's ORIGINAL position.  The causal
        attention mask is the attention_fn's job in that case
        (``attention_is_causal=True``).

        Serving path (ISSUE 9): ``kv_cache`` is a per-trace paged-cache
        hook (``stoke_tpu.serving.kv_cache.PagedAttentionHook``) supplying
        one attention fn per layer via ``layer_attention(i)`` — each
        writes that layer's fresh K/V into the block pool and (in decode
        mode) attends over the gathered cached blocks.  ``decode=True``
        marks the incremental single-token forward: ``input_ids`` is
        ``[B, 1]``, ``positions`` carries each slot's current position,
        and the hook's updated page arrays are read back by the caller
        after apply (the hook threads them functionally through one
        trace).  Incremental decode matches the full-sequence forward
        token-for-token (tests/test_serving.py decode-parity).  The
        causal mask is the hook's job, so no in-model bias is built."""
        size: BertSize = BERT_SIZES[self.size_name]
        B, L = input_ids.shape
        if decode and kv_cache is None:
            raise ValueError(
                "GPT: decode=True needs a kv_cache hook — the incremental "
                "forward reads/writes the paged KV-cache "
                "(stoke_tpu.serving.kv_cache.PagedAttentionHook)"
            )
        if decode and L != 1:
            raise ValueError(
                f"GPT: decode=True is single-token incremental decode; got "
                f"sequence length {L} (prefill runs kv_cache without decode)"
            )
        if decode and positions is None:
            raise ValueError(
                "GPT: decode=True needs explicit positions (each slot's "
                "current cache position selects its position embedding)"
            )
        if kv_cache is not None and self.moe_num_experts > 0:
            raise NotImplementedError(
                "GPT: the paged KV-cache serving path supports dense FFN "
                "blocks only (no MoE routing state in the cache)"
            )
        if L > self.max_len:
            # XLA would silently clamp out-of-range position indices,
            # collapsing every position past max_len onto one embedding
            raise ValueError(
                f"GPT: sequence length {L} exceeds max_len={self.max_len}"
            )
        tok_emb = nn.Embed(self.vocab_size, size.hidden, name="tok_emb")
        h = tok_emb(input_ids)
        if positions is None:
            pos = jnp.arange(L)[None, :]
        else:
            # concrete position ids are validated eagerly — XLA's gather
            # would silently CLAMP out-of-range ids onto the max_len-1
            # embedding (same failure mode as the L > max_len guard above);
            # traced positions cannot be checked without a device sync
            if not isinstance(positions, jax.core.Tracer):
                pmax = int(np.max(np.asarray(positions)))
                if pmax >= self.max_len:
                    raise ValueError(
                        f"GPT: positions contain id {pmax} >= "
                        f"max_len={self.max_len}"
                    )
            pos = jnp.asarray(positions)
            if pos.ndim == 1:
                pos = pos[None, :]
        h = h + nn.Embed(self.max_len, size.hidden, name="pos_emb")(pos)
        h = nn.Dropout(self.dropout_rate)(h, deterministic=not train)
        if self.attention_is_causal or kv_cache is not None:
            # cache-aware attention (serving): masking — causal + prompt
            # padding in prefill, context-length in decode — is the
            # kv_cache hook's job, exactly like a causal attention_fn's
            bias = None
        else:
            causal = jnp.tril(jnp.ones((L, L), bool))
            bias = jnp.where(causal, 0.0, -1e9)[None, None, :, :].astype(h.dtype)
        block = TransformerBlock
        moe_block = None
        if self.moe_num_experts > 0:
            from stoke_tpu.models.moe import MoETransformerBlock

            if self.moe_every < 1:
                raise ValueError(
                    f"GPT: moe_every must be >= 1, got {self.moe_every}"
                )
            if size.num_layers // self.moe_every == 0:
                raise ValueError(
                    f"GPT: moe_every={self.moe_every} selects no layer in a "
                    f"{size.num_layers}-layer model — the MoE option would "
                    f"silently train fully dense"
                )
            moe_block = MoETransformerBlock
        if self.remat:
            block = nn.remat(TransformerBlock, static_argnums=(3,))
            if moe_block is not None:
                moe_block = nn.remat(MoETransformerBlock, static_argnums=(3,))
        for i in range(size.num_layers):
            use_moe = (
                moe_block is not None and (i + 1) % self.moe_every == 0
            )
            if use_moe:
                h = moe_block(
                    size.hidden, size.heads, size.ff, self.moe_num_experts,
                    self.dropout_rate, self.moe_capacity_factor,
                    self.attention_fn, self.moe_router_noise,
                    self.moe_top_k, name=f"layer_{i}",
                )(h, bias, not train)
            else:
                # cache-aware serving: each layer gets its OWN attention fn
                # from the hook (it addresses that layer's page plane) —
                # attention_fn is not a parameter, so the param tree is
                # identical to the training forward's
                attn_fn = (
                    self.attention_fn
                    if kv_cache is None
                    else kv_cache.layer_attention(i)
                )
                h = block(
                    size.hidden, size.heads, size.ff, self.dropout_rate,
                    attn_fn, name=f"layer_{i}",
                )(h, bias, not train)
        h = nn.LayerNorm(epsilon=1e-5, name="ln_final")(h)
        if self.chunked_head:
            if not self.tie_embeddings:
                raise ValueError(
                    "GPT: chunked_head requires tie_embeddings=True (the "
                    "chunked loss re-applies the tied embedding per chunk)"
                )
            return h, tok_emb.embedding
        if self.tie_embeddings:
            return tok_emb.attend(h)
        return nn.Dense(self.vocab_size, name="lm_head")(h)


GPTTiny = partial(GPT, size_name="tiny")
GPTBase = partial(GPT, size_name="base")


def causal_lm_loss(logits, input_ids, mask=None):
    """Next-token cross entropy: predict token t+1 from positions ≤ t.
    ``mask`` (optional [B, L] 0/1) excludes padding targets."""
    import optax

    targets = input_ids[:, 1:]
    logits = logits[:, :-1]
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    if mask is not None:
        w = mask[:, 1:].astype(losses.dtype)
        return (losses * w).sum() / jnp.maximum(w.sum(), 1.0)
    return losses.mean()

"""Mixture-of-Experts FFN with expert parallelism.

Completes the parallelism menu (dp/tp/sp/pp/**ep**) — capability upside
beyond the reference (SURVEY.md §2.8: no expert parallelism).  The design
keeps the framework's theme: expert parallelism is *placement*, not code.
Expert weights are stacked on a leading expert dimension; shard that
dimension over a mesh ``expert`` axis with a partition rule
(:func:`moe_expert_parallel_rules`) and GSPMD lowers the dispatch/combine
einsums to the all-to-all pattern — no hand-written collectives.

Routing is standard switch-style top-1 with a capacity limit: each token
goes to its argmax expert; experts accept at most
``ceil(tokens/E) * capacity_factor`` tokens; overflow tokens pass through
the residual unchanged (combine weight 0).  Dispatch/combine are one-hot
einsums (MXU-friendly, static shapes — no gather/scatter).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class MoEFFN(nn.Module):
    """Switch-routed expert FFN block (drop-in for a dense FFN).

    Args:
        hidden: model width (input/output dim).
        ff: per-expert feed-forward width.
        num_experts: expert count E (shard over the mesh ``expert`` axis via
            :func:`moe_expert_parallel_rules` for EP).
        capacity_factor: per-expert capacity = ceil(N/E) * factor.
        router_noise: train-time logit jitter (load balancing aid); needs the
            ``router`` rng stream when > 0.
    """

    hidden: int
    ff: int
    num_experts: int = 8
    capacity_factor: float = 1.25
    router_noise: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = True):
        # Grouped dispatch (Switch/Mesh-TF layout): tokens are routed within
        # per-example groups of S = L tokens, so the one-hot dispatch/combine
        # tensors are [G, S, E, C] with C ≈ S/E — LINEAR in total tokens
        # (an ungrouped [N, E, N/E] layout would be quadratic and OOM at
        # real sequence lengths).
        G, S, H = x.shape
        E = self.num_experts
        C = max(1, int(np.ceil(S / E) * self.capacity_factor))

        logits = nn.Dense(E, use_bias=False, name="router")(x)  # [G, S, E]
        if self.router_noise > 0.0 and train:
            key = self.make_rng("router")
            logits = logits + self.router_noise * jax.random.normal(
                key, logits.shape, logits.dtype
            )
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)  # [G, S]
        gate = jnp.take_along_axis(probs, expert_idx[..., None], axis=-1)[..., 0]

        # capacity: position of each token within its expert's per-group queue
        assign = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [G, S, E]
        position = (jnp.cumsum(assign, axis=1) - 1.0) * assign
        pos_in_expert = jnp.sum(position, axis=-1)  # [G, S]
        keep = pos_in_expert < C
        gate = gate * keep

        # dispatch/combine: [G, S, E, C] one-hot (static shapes, MXU)
        pos_oh = jax.nn.one_hot(
            pos_in_expert.astype(jnp.int32), C, dtype=jnp.float32
        )
        dispatch = (
            assign[..., None] * pos_oh[:, :, None, :] * keep[..., None, None]
        )
        combine = dispatch * gate[..., None, None]

        # route → expert MLPs (weights stacked on the expert dim) → return
        expert_in = jnp.einsum(
            "gsec,gsh->egch", dispatch.astype(x.dtype), x
        )  # [E, G, C, H]
        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(), (E, H, self.ff), jnp.float32
        ).astype(x.dtype)
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(), (E, self.ff, H), jnp.float32
        ).astype(x.dtype)
        h = jax.nn.gelu(jnp.einsum("egch,ehf->egcf", expert_in, w_in))
        expert_out = jnp.einsum("egcf,efh->egch", h, w_out)
        return jnp.einsum(
            "gsec,egch->gsh", combine.astype(x.dtype), expert_out
        )


def moe_expert_parallel_rules(expert_axis: str = "expert") -> Tuple:
    """Partition rules sharding the stacked expert weights over the mesh
    ``expert`` axis (for ``PartitionRulesConfig``); the router stays
    replicated.  With these placements GSPMD lowers the dispatch/combine
    einsums to the expert all-to-all."""
    return (
        (r"w_in$", (expert_axis, None, None)),
        (r"w_out$", (expert_axis, None, None)),
    )


class MoETransformerBlock(nn.Module):
    """Transformer block whose FFN is a switch MoE (attention unchanged) —
    composes with the BERT/GPT encoders via manual stacking or as a
    reference for building MoE models."""

    hidden: int
    heads: int
    ff: int
    num_experts: int = 8
    dropout_rate: float = 0.1
    capacity_factor: float = 1.25
    attention_fn: Optional[Callable] = None
    router_noise: float = 0.0

    @nn.compact
    def __call__(self, x, bias, deterministic: bool):
        from stoke_tpu.models.bert import MultiHeadAttention, dense_attention

        attn = self.attention_fn or dense_attention
        y = MultiHeadAttention(
            self.hidden, self.heads, self.dropout_rate, attn, name="attention"
        )(x, bias, deterministic)
        y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=1e-12, name="ln_attn")(x + y)
        y = MoEFFN(
            self.hidden, self.ff, self.num_experts, self.capacity_factor,
            self.router_noise, name="moe",
        )(x, train=not deterministic)
        y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        return nn.LayerNorm(epsilon=1e-12, name="ln_ff")(x + y)

"""Mixture-of-Experts FFN with expert parallelism.

Completes the parallelism menu (dp/tp/sp/pp/**ep**) — capability upside
beyond the reference (SURVEY.md §2.8: no expert parallelism).  The design
keeps the framework's theme: expert parallelism is *placement*, not code.
Expert weights are stacked on a leading expert dimension; shard that
dimension over a mesh ``expert`` axis with a partition rule
(:func:`moe_expert_parallel_rules`) and GSPMD lowers the dispatch/combine
einsums to the all-to-all pattern — no hand-written collectives.

Routing is switch-style top-k (k=1 default; k=2 gives GShard-style routing
with renormalized gates) with a capacity limit: experts accept at most
``ceil(tokens/E) * capacity_factor`` tokens per choice-priority order
(first choices fill capacity before second choices); overflow tokens pass
through the residual unchanged (combine weight 0).  Dispatch/combine are
one-hot einsums (MXU-friendly, static shapes — no gather/scatter).

**Load balancing**: the router computes the Switch-Transformer auxiliary
loss ``aux = E · Σ_e f_e · P_e`` (f_e = fraction of tokens whose first
choice is expert e, P_e = mean router probability of e; minimized at 1.0
by the uniform assignment) and sows it into the flax ``"losses"``
collection.  The training engine adds sown losses to the objective with
the facade's ``aux_loss_weight`` (default 0.01) — without this term,
top-1 routing collapses onto a few experts in real training.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class MoEFFN(nn.Module):
    """Switch-routed expert FFN block (drop-in for a dense FFN).

    Args:
        hidden: model width (input/output dim).
        ff: per-expert feed-forward width.
        num_experts: expert count E (shard over the mesh ``expert`` axis via
            :func:`moe_expert_parallel_rules` for EP).
        capacity_factor: per-expert capacity = ceil(top_k·N/E) * factor
            (scaled by top_k per the GShard convention, so k=2 at the
            default factor does not structurally drop second choices).
        router_noise: train-time logit jitter (load balancing aid); needs the
            ``router`` rng stream when > 0.
        top_k: experts per token (1 = Switch, 2 = GShard-style with
            renormalized gates).
    """

    hidden: int
    ff: int
    num_experts: int = 8
    capacity_factor: float = 1.25
    router_noise: float = 0.0
    top_k: int = 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        # Grouped dispatch (Switch/Mesh-TF layout): tokens are routed within
        # per-example groups of S = L tokens, so the one-hot dispatch/combine
        # tensors are [G, S, E, C] with C ≈ S/E — LINEAR in total tokens
        # (an ungrouped [N, E, N/E] layout would be quadratic and OOM at
        # real sequence lengths).
        G, S, H = x.shape
        E = self.num_experts
        k = self.top_k
        if not 1 <= k <= E:
            raise ValueError(f"MoEFFN: top_k must be in [1, {E}], got {k}")
        # GShard convention: tokens produce k assignments, so per-expert
        # capacity scales with k — otherwise top-2 at the default factor
        # would structurally drop every second choice
        C = max(1, int(np.ceil(k * S / E) * self.capacity_factor))

        logits = nn.Dense(E, use_bias=False, name="router")(x)  # [G, S, E]
        if self.router_noise > 0.0 and train:
            key = self.make_rng("router")
            logits = logits + self.router_noise * jax.random.normal(
                key, logits.shape, logits.dtype
            )
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        topk_probs, topk_idx = jax.lax.top_k(probs, k)  # [G, S, k]
        if k > 1:
            gates = topk_probs / jnp.maximum(
                jnp.sum(topk_probs, axis=-1, keepdims=True), 1e-9
            )
        else:
            gates = topk_probs

        # Switch load-balancing loss: E · Σ_e f_e·P_e (f from first choices,
        # P the mean router prob; ≥ 1 with equality at uniform).  Sown with
        # an overwriting reduce_fn so the collection stays a stable scalar
        # across steps (the engine folds it into the objective and the
        # facade surfaces it via ``aux_losses``).
        assign1 = jax.nn.one_hot(topk_idx[..., 0], E, dtype=jnp.float32)
        f_e = jnp.mean(assign1, axis=(0, 1))           # [E]
        p_e = jnp.mean(probs, axis=(0, 1))             # [E]
        aux = jnp.float32(E) * jnp.sum(f_e * p_e)
        self.sow(
            "losses", "aux_loss", aux,
            reduce_fn=lambda prev, new: new,
            init_fn=lambda: jnp.float32(0.0),
        )

        # capacity: queue position per (token, choice), choice-major priority
        # (all first choices claim capacity before any second choice)
        assign_k = jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)  # [G,S,k,E]
        prio = assign_k.transpose(0, 2, 1, 3).reshape(G, k * S, E)
        position = (jnp.cumsum(prio, axis=1) - 1.0) * prio
        pos_tok = jnp.sum(position, axis=-1)           # [G, k*S]
        pos_tok = pos_tok.reshape(G, k, S).transpose(0, 2, 1)  # [G, S, k]
        keep = pos_tok < C
        gates = gates * keep

        # dispatch/combine: [G, S, E, C] one-hot (static shapes, MXU)
        pos_oh = jax.nn.one_hot(
            pos_tok.astype(jnp.int32), C, dtype=jnp.float32
        )  # [G, S, k, C]
        dispatch = jnp.einsum(
            "gsje,gsjc->gsec", assign_k * keep[..., None], pos_oh
        )
        combine = jnp.einsum(
            "gsje,gsjc->gsec", assign_k * gates[..., None], pos_oh
        )

        # route → expert MLPs (weights stacked on the expert dim) → return
        expert_in = jnp.einsum(
            "gsec,gsh->egch", dispatch.astype(x.dtype), x
        )  # [E, G, C, H]
        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(), (E, H, self.ff), jnp.float32
        ).astype(x.dtype)
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(), (E, self.ff, H), jnp.float32
        ).astype(x.dtype)
        h = jax.nn.gelu(jnp.einsum("egch,ehf->egcf", expert_in, w_in))
        expert_out = jnp.einsum("egcf,efh->egch", h, w_out)
        return jnp.einsum(
            "gsec,egch->gsh", combine.astype(x.dtype), expert_out
        )


def moe_expert_parallel_rules(expert_axis: str = "expert") -> Tuple:
    """Partition rules sharding the stacked expert weights over the mesh
    ``expert`` axis (for ``PartitionRulesConfig``); the router stays
    replicated.  With these placements GSPMD lowers the dispatch/combine
    einsums to the expert all-to-all."""
    return (
        (r"w_in$", (expert_axis, None, None)),
        (r"w_out$", (expert_axis, None, None)),
    )


class MoETransformerBlock(nn.Module):
    """Transformer block whose FFN is a switch MoE (attention unchanged) —
    composes with the BERT/GPT encoders via manual stacking or as a
    reference for building MoE models."""

    hidden: int
    heads: int
    ff: int
    num_experts: int = 8
    dropout_rate: float = 0.1
    capacity_factor: float = 1.25
    attention_fn: Optional[Callable] = None
    router_noise: float = 0.0
    top_k: int = 1

    @nn.compact
    def __call__(self, x, bias, deterministic: bool):
        from stoke_tpu.models.bert import MultiHeadAttention, dense_attention

        attn = self.attention_fn or dense_attention
        y = MultiHeadAttention(
            self.hidden, self.heads, self.dropout_rate, attn, name="attention"
        )(x, bias, deterministic)
        y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=1e-12, name="ln_attn")(x + y)
        y = MoEFFN(
            self.hidden, self.ff, self.num_experts, self.capacity_factor,
            self.router_noise, self.top_k, name="moe",
        )(x, train=not deterministic)
        y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        return nn.LayerNorm(epsilon=1e-12, name="ln_ff")(x + y)

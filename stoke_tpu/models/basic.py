"""BasicNN: the small CIFAR-10 CNN of the reference quick-start
(README.md:100-115 — two conv+pool blocks, three dense layers)."""

from __future__ import annotations

import flax.linen as nn


class BasicNN(nn.Module):
    """Reference quick-start CNN (README.md:100-115): conv(6,5x5) → pool →
    conv(16,5x5) → pool → fc120 → fc84 → fc(num_classes).  NHWC layout
    (TPU-native; the reference's NCHW is a CUDA idiom)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(6, (5, 5), padding="VALID")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120)(x))
        x = nn.relu(nn.Dense(84)(x))
        return nn.Dense(self.num_classes)(x)

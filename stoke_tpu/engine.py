"""Functional core: compiled train/eval steps behind the imperative facade.

This module solves SURVEY.md §7 hard part #1 — keeping the reference's
imperative 4-call contract (``model → loss → backward → step``,
stoke/stoke.py:853-1040) over a purely functional JAX core — with a *lazy
fused step*:

- ``model(x)`` (train mode) returns a :class:`DeferredOutput` handle and
  stashes the batch; nothing runs.
- ``loss(out, y)`` runs ONE compiled function that does forward + loss +
  grad + accumulate-into-buffer (micro-step), returning device-scalar losses.
  This is the TPU answer to the reference's per-micro-batch synchronous
  ``.item()`` + allreduce (distributed.py:619-646): the loss stays on device,
  the gradient all-reduce/reduce-scatter is compiler-inserted, and there is
  exactly one dispatch per micro-batch.
- ``backward(loss)`` commits the accumulated buffer (pointer swap — the
  accumulation already happened inside the compiled step; un-committed
  buffers are simply dropped, preserving "no backward → no grads").
- ``step()`` runs the compiled apply: unscale → clip → optimizer update →
  zero the buffer, under the sharding rules of the active tier.

Precision policy (SURVEY.md §3.2 observation (c)): params live in fp32
(master weights), compute runs in the policy dtype (bf16 natively on TPU; no
loss scaler needed — fp32-range exponent).  fp16 gets a *functional* dynamic
loss scaler (scale/growth_count carried as device state) replacing
``torch.cuda.amp.GradScaler`` (reference fp16.py:694-806).

Gradient accumulation lives inside the compiled step as a buffer add
(reference: Python-side counters + DDP ``no_sync``, stoke.py:326-344,
distributed.py:648-669 — no ``no_sync`` needed here: nothing eagerly syncs).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from stoke_tpu.configs import (
    ActivationCheckpointingConfig,
    ClipGradConfig,
    ClipGradNormConfig,
    PrecisionConfig,
    PrecisionOptions,
    StokeOptimizer,
)
from stoke_tpu.parallel.zero import make_transport
from stoke_tpu.parallel.sharding import ShardingRules, place_global_tree
from stoke_tpu.telemetry.tracing import trace_span
from stoke_tpu.telemetry.health import compute_sentinels
from stoke_tpu.telemetry.numerics import compute_group_stats
from stoke_tpu.utils.trees import tree_cast, tree_finite, tree_zeros_like


# --------------------------------------------------------------------------- #
# Model adapters
# --------------------------------------------------------------------------- #


class ModelAdapter:
    """Contract between the facade and any model flavor.

    ``variables`` is a dict of collections with a ``"params"`` entry (flax
    convention); gradients are taken w.r.t. ``variables["params"]`` only.
    ``apply_train`` may update non-param collections (e.g. BatchNorm
    ``batch_stats`` — the reference needs SyncBatchNorm conversion for this,
    distributed.py:575-579; under jit-GSPMD the batch moments are computed
    over the logically-global batch, so cross-replica sync is automatic).
    """

    def apply_train(
        self, variables: Dict[str, Any], rng, args: tuple, kwargs: dict
    ) -> Tuple[Any, Dict[str, Any]]:
        raise NotImplementedError

    def apply_eval(self, variables: Dict[str, Any], args: tuple, kwargs: dict) -> Any:
        raise NotImplementedError


class FlaxModelAdapter(ModelAdapter):
    """Adapter for ``flax.linen.Module`` models.

    Args:
        module: the linen module.
        train_kwargs / eval_kwargs: extra kwargs distinguishing train/eval
            application (e.g. ``{"train": True}`` / ``{"train": False}`` for
            modules with dropout/BN) — replaces torch's implicit
            ``module.train()/eval()`` mode bit the reference relies on.
        rng_keys: names of rng streams to thread (default ``("dropout",)``).
    """

    def __init__(
        self,
        module,
        train_kwargs: Optional[dict] = None,
        eval_kwargs: Optional[dict] = None,
        rng_keys: Sequence[str] = ("dropout",),
    ):
        self.module = module
        self.train_kwargs = dict(train_kwargs or {})
        self.eval_kwargs = dict(eval_kwargs or {})
        self.rng_keys = tuple(rng_keys)

    def apply_train(self, variables, rng, args, kwargs):
        mutable = [k for k in variables.keys() if k != "params"]
        rngs = None
        if self.rng_keys:
            keys = jax.random.split(rng, len(self.rng_keys))
            rngs = {name: keys[i] for i, name in enumerate(self.rng_keys)}
        merged = {**kwargs, **self.train_kwargs}
        if mutable:
            out, updated = self.module.apply(
                variables, *args, rngs=rngs, mutable=mutable, **merged
            )
            return out, dict(updated)
        out = self.module.apply(variables, *args, rngs=rngs, **merged)
        return out, {}

    def apply_eval(self, variables, args, kwargs):
        merged = {**kwargs, **self.eval_kwargs}
        return self.module.apply(variables, *args, **merged)


class FunctionalModelAdapter(ModelAdapter):
    """Adapter for a plain callable ``fn(params, *args, **kwargs) -> out``
    (no rng, no mutable collections, identical train/eval behavior)."""

    def __init__(self, fn: Callable, eval_fn: Optional[Callable] = None):
        self.fn = fn
        self.eval_fn = eval_fn or fn

    def apply_train(self, variables, rng, args, kwargs):
        return self.fn(variables["params"], *args, **kwargs), {}

    def apply_eval(self, variables, args, kwargs):
        return self.eval_fn(variables["params"], *args, **kwargs)


def as_adapter(model: Any, **adapter_kwargs) -> ModelAdapter:
    """Coerce user input to a ModelAdapter: an adapter instance, a flax
    module (has ``.apply``), or a plain callable."""
    if isinstance(model, ModelAdapter):
        return model
    if hasattr(model, "apply") and hasattr(model, "init"):
        return FlaxModelAdapter(model, **adapter_kwargs)
    if callable(model):
        return FunctionalModelAdapter(model)
    raise TypeError(
        f"Stoke -- model must be a flax Module, a callable, or a ModelAdapter; "
        f"got {type(model)}"
    )


# --------------------------------------------------------------------------- #
# Deferred outputs (lazy model() handles)
# --------------------------------------------------------------------------- #


class DeferredOutput:
    """Lazy handle returned by ``Stoke.model`` in train mode.

    Records an extraction *path* (``out[0].logits`` → ``(("getitem", 0),
    ("getattr", "logits"))``) instead of values, so ``loss()`` can substitute
    the real forward output inside the compiled fused step — avoiding the
    extra forward pass an eager ``model()`` would force.  ``.value``
    materializes through a separate compiled forward with the SAME rng the
    fused step will use, so dropout masks agree.
    """

    __slots__ = ("_materialize", "_token", "_path")

    def __init__(self, materialize_fn, token: int, path: Tuple = ()):
        object.__setattr__(self, "_materialize", materialize_fn)
        object.__setattr__(self, "_token", token)
        object.__setattr__(self, "_path", path)

    def __getitem__(self, key):
        return DeferredOutput(
            self._materialize, self._token, self._path + (("getitem", key),)
        )

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeferredOutput(
            self._materialize, self._token, self._path + (("getattr", name),)
        )

    @property
    def value(self):
        """Materialize the real output (runs a compiled train-mode forward)."""
        return apply_path(self._materialize(self._token), self._path)

    def __array__(self, dtype=None):
        return np.asarray(self.value, dtype=dtype)

    def __repr__(self):
        return f"DeferredOutput(token={self._token}, path={self._path})"


def is_deferred(x) -> bool:
    return isinstance(x, DeferredOutput)


def apply_path(out, path: Tuple) -> Any:
    for kind, key in path:
        out = out[key] if kind == "getitem" else getattr(out, key)
    return out


# --------------------------------------------------------------------------- #
# Loss pytree helpers (multi-loss support; reference stoke.py:872-912)
# --------------------------------------------------------------------------- #


def flatten_losses(loss_result: Any) -> Tuple[list, Any]:
    """User loss fns may return a scalar, tuple/list, or dict of scalars
    (reference supports single + list/tuple, stoke.py:891-902).  Returns
    (leaves, treedef)."""
    leaves, treedef = jax.tree_util.tree_flatten(loss_result)
    return leaves, treedef


# --------------------------------------------------------------------------- #
# Precision policy
# --------------------------------------------------------------------------- #


class PrecisionPolicy(NamedTuple):
    """Dtype policy: fp32 master params, policy compute dtype, fp32 outputs
    (replaces autocast contexts + GradScaler, reference fp16.py:694-806)."""

    param_dtype: Any
    compute_dtype: Optional[Any]  # None = no cast (full precision)
    output_dtype: Optional[Any]
    scaled: bool  # True only for fp16 (dynamic loss scaler active)

    @staticmethod
    def make(option: PrecisionOptions, cfg: PrecisionConfig) -> "PrecisionPolicy":
        if option is PrecisionOptions.full:
            return PrecisionPolicy(jnp.dtype(cfg.param_dtype), None, None, False)
        if option is PrecisionOptions.bf16:
            return PrecisionPolicy(
                jnp.dtype(cfg.param_dtype),
                jnp.bfloat16,
                jnp.dtype(cfg.output_dtype),
                False,
            )
        if option is PrecisionOptions.fp16:
            return PrecisionPolicy(
                jnp.dtype(cfg.param_dtype),
                jnp.float16,
                jnp.dtype(cfg.output_dtype),
                True,
            )
        raise ValueError(option)

    def cast_compute(self, tree):
        return tree_cast(tree, self.compute_dtype)

    def cast_output(self, tree):
        return tree_cast(tree, self.output_dtype)


def init_scaler_state(cfg: PrecisionConfig) -> Dict[str, Any]:
    """Dynamic loss-scaler state (functional GradScaler, reference
    fp16.py:731-748).  Created as host numpy so construction never touches
    the default accelerator backend (the facade places it explicitly).

    With ``num_losses > 1`` (reference Apex per-loss scalers,
    fp16.py:656-691) every field becomes a ``[num_losses]`` vector and a
    per-loss ``finite`` flag vector is carried: the accumulate step ANDs in
    each loss's backward finiteness, the apply step feeds the flags to the
    vectorized scaler update and resets them."""
    if cfg.num_losses > 1:
        n = cfg.num_losses
        return {
            "scale": np.full(n, cfg.init_scale, np.float32),
            "growth_count": np.zeros(n, np.int32),
            "finite": np.ones(n, np.bool_),
        }
    return {
        "scale": np.float32(cfg.init_scale),
        "growth_count": np.int32(0),
    }


def _scaler_update(state, finite, cfg: PrecisionConfig):
    """GradScaler.update() semantics (reference fp16.py:805-806): grow scale
    after ``growth_interval`` consecutive finite steps, back off on overflow.
    Elementwise, so a ``[num_losses]`` scale vector with a per-loss finite
    vector updates each loss's scaler independently."""
    grew = state["growth_count"] + 1 >= cfg.growth_interval
    new_scale = jnp.where(
        finite,
        jnp.where(grew, state["scale"] * cfg.growth_factor, state["scale"]),
        jnp.maximum(state["scale"] * cfg.backoff_factor, cfg.min_scale),
    )
    new_count = jnp.where(finite & ~grew, state["growth_count"] + 1, 0)
    return {"scale": new_scale, "growth_count": new_count}


# --------------------------------------------------------------------------- #
# Gradient clipping (reference fp16.py:84-156 dispatch)
# --------------------------------------------------------------------------- #


def clip_gradients(grads, grad_clip) -> Any:
    """Clip on the (already unscaled, logically-global) gradient pytree.

    The reference needs five backend-specific clip implementations
    (fp16.py:84-156: plain / scaler-unscaled / OSS synced-norm / FSDP
    model-level / horovod-synchronize-first); under SPMD jit the gradients
    are logically global, so one implementation serves every tier.
    """
    if grad_clip is None:
        return grads
    if isinstance(grad_clip, ClipGradConfig):
        v = grad_clip.clip_value
        return jax.tree_util.tree_map(lambda g: jnp.clip(g, -v, v), grads)
    if isinstance(grad_clip, ClipGradNormConfig):
        p = grad_clip.norm_type
        leaves = jax.tree_util.tree_leaves(grads)
        if p == np.inf:
            norm = jnp.max(jnp.stack([jnp.max(jnp.abs(l)) for l in leaves]))
        else:
            norm = (
                jnp.sum(
                    jnp.stack(
                        [jnp.sum(jnp.abs(l.astype(jnp.float32)) ** p) for l in leaves]
                    )
                )
                ** (1.0 / p)
            )
        factor = jnp.minimum(1.0, grad_clip.max_norm / (norm + 1e-6))
        return jax.tree_util.tree_map(lambda g: g * factor, grads)
    raise TypeError(f"unknown grad_clip {type(grad_clip)}")


# --------------------------------------------------------------------------- #
# Optimizer build (reference extensions.py:30-78 BaseOptimizer)
# --------------------------------------------------------------------------- #


def build_optimizer(optimizer: Any) -> optax.GradientTransformation:
    """Instantiate the optimizer from a StokeOptimizer TypedDict (constructor
    + kwargs, reference configs.py:754-770) or accept an already-built optax
    GradientTransformation."""
    if isinstance(optimizer, optax.GradientTransformation):
        return optimizer
    if isinstance(optimizer, dict) and "optimizer" in optimizer:
        ctor = optimizer["optimizer"]
        kwargs = optimizer.get("optimizer_kwargs", {})
        built = ctor(**kwargs)
        if not isinstance(built, optax.GradientTransformation):
            raise TypeError(
                f"Stoke -- StokeOptimizer['optimizer'] must construct an optax "
                f"GradientTransformation, got {type(built)}"
            )
        return built
    if callable(optimizer):
        built = optimizer()
        if isinstance(built, optax.GradientTransformation):
            return built
    raise TypeError(
        "Stoke -- optimizer must be an optax.GradientTransformation or a "
        "StokeOptimizer dict {'optimizer': ctor, 'optimizer_kwargs': {...}}"
    )


# --------------------------------------------------------------------------- #
# The engine
# --------------------------------------------------------------------------- #


class StepEngine:
    """Owns the compiled step functions and the sharding contract.

    One engine instance per ``Stoke`` facade.  All state (variables /
    opt_state / grad buffer / scaler / rng) is held by the *facade* and passed
    through; the engine is stateless apart from its jit caches, keeping the
    functional core testable in isolation.
    """

    def __init__(
        self,
        adapter: ModelAdapter,
        loss_fn: Callable,
        optimizer: optax.GradientTransformation,
        *,
        precision: PrecisionPolicy,
        precision_config: PrecisionConfig,
        grad_accum: int,
        grad_clip,
        rules: Optional[ShardingRules],
        remat: Optional[ActivationCheckpointingConfig] = None,
        offload_optimizer: Optional[Any] = None,
        offload_params: Optional[Any] = None,
        loss_weights: Optional[Any] = None,
        aux_loss_weight: float = 0.01,
        comm: Optional[Any] = None,
        health: Optional[Any] = None,
        numerics: Optional[Any] = None,
    ):
        self.adapter = adapter
        self.loss_fn = loss_fn
        self.loss_weights = loss_weights
        self.aux_loss_weight = float(aux_loss_weight)
        self.optimizer = optimizer
        self.precision = precision
        self.precision_config = precision_config
        self.grad_accum = int(grad_accum)
        self.grad_clip = grad_clip
        self.rules = rules
        self.remat = remat
        self.offload_optimizer = offload_optimizer
        self.offload_params = offload_params
        # gradient-transport layer (ISSUE 2): quantized collectives with
        # error feedback, applied ONCE per optimizer step inside the apply
        # core.  A None comm config (or dtype="fp32") makes the transport a
        # structural pass-through: the apply program is byte-for-byte the
        # same as before the layer existed.  Under the sharded tiers (or
        # CommConfig.shard_updates) the factory returns the ISSUE 8
        # weight-update-sharded variant: quantized reduce-scatter, sharded
        # EF residual, shard-local update, param all-gather.
        self.comm = comm
        self.transport = make_transport(comm, rules)
        # health sentinels (ISSUE 3): when on, the apply core additionally
        # returns a packed per-step diagnostics vector computed INSIDE the
        # same compiled program (zero extra dispatches).  When off, the
        # sentinel slot is an empty pytree (None) and a None loss input is
        # threaded — both contribute nothing to the flattened jit
        # arguments, so the compiled programs are bit-identical to a build
        # without the feature.
        self.health = health
        self.sentinels_enabled = bool(
            health is not None and getattr(health, "sentinels", False)
        )
        # per-layer numerics observatory (ISSUE 12): when on, the apply
        # core additionally returns a fixed-layout [n_groups, n_stats]
        # group-stats matrix computed INSIDE the same compiled program —
        # the sentinel discipline again: zero extra dispatches, and a
        # None slot (empty pytree) when off keeps the compiled programs
        # bit-identical to a build without the feature.
        self.numerics = numerics
        self.numerics_enabled = bool(
            numerics is not None and getattr(numerics, "grad_stats", False)
        )
        # compiled-program invocation counter: one increment per device
        # dispatch issued by this engine.  The health acceptance criterion
        # ("sentinels add zero dispatches") asserts equality of this
        # counter across health-on/off runs.
        self.dispatch_count = 0
        self._accum_cache: Dict[Any, Callable] = {}
        self._fwd_cache: Dict[Any, Callable] = {}
        self._loss_cache: Dict[Any, Callable] = {}
        self._apply_fn: Optional[Callable] = None
        # input-shape signatures per compiled program, for structural
        # recompile detection (telemetry): a warm program dispatched with a
        # NEW signature forces a silent XLA recompile.  The facade assigns
        # THIS engine's tracker (instance-scoped: another facade's shape
        # churn in the same process must not be charged to this run)
        self._shape_sigs: Dict[Any, set] = {}
        self._compile_tracker = None
        # step-time attribution (ISSUE 4): CostCardCache assigned by the
        # facade when an AttributionConfig is supplied.  Each dispatch
        # site reports (program key + shape signature, jitted fn, live
        # args) so the cache can run ONE cost_analysis per program
        # signature and account analytic FLOPs/bytes per dispatch.  None
        # -> zero bookkeeping, programs untouched.
        self._attribution = None
        # memory observatory (ISSUE 19): assigned by the facade when a
        # MemoryConfig is supplied.  _aot_call reports (program, fn, live
        # args, signature) so the observatory's CostCardCache can run ONE
        # XLA memory_analysis per program signature — temp/argument/
        # output peaks for the OOM pre-flight and the memory-drift gate.
        # None -> zero bookkeeping, programs untouched.
        self._memory = None
        # persistent AOT compile cache (ISSUE 6): assigned by the facade
        # when a CompileConfig is supplied.  Each step-program dispatch
        # site resolves its callable through _aot_call: with a cache, the
        # first dispatch per (program key, shape signature) lowers the
        # jitted fn and checks the HLO-keyed program ledger (warm-start
        # hit accounting; the persistent XLA cache serves the backend
        # compile), then dispatches through the jitted fn as always.
        # None -> zero bookkeeping, dispatch untouched.
        self._compile_cache = None
        # fault injector (ISSUE 7): assigned by the facade when a
        # ResilienceConfig arms a chaos spec.  _aot_call (the funnel every
        # dispatch site resolves its callable through) gives it a
        # pre-dispatch hook — host-side only, the compiled programs are
        # untouched.  None -> dispatch untouched.
        self._chaos = None
        # program-audit ledger (ISSUE 15): the FIRST dispatch per
        # (program, structure key, shape signature) records an abstract
        # spec — program name, jitted fn, ShapeDtypeStruct arg tree,
        # declared donations — so Stoke.audit() can re-lower and
        # statically check every program this engine actually ran,
        # without retaining live buffers (the next step's donation
        # deletes them) and without dispatching anything.  Purely
        # host-side bookkeeping: compiled programs and dispatch counts
        # are untouched (asserted in tests/test_analysis.py).
        self._audit_specs: list = []
        self._audit_seen: set = set()
        # set when the spec cap dropped a NEW program signature: the
        # audit surfaces it as a note — "zero findings" must stay
        # distinguishable from "not audited"
        self._audit_truncated = False
        # per-program declared donations, recorded by _jit_program at
        # the ONE place each build states them — the audit's donation-
        # integrity check reads this ledger (a hand-maintained mirror
        # of the _build_* donate_argnums would drift)
        self._program_donations: Dict[str, Tuple[int, ...]] = {}
        # shardings, resolved lazily once variables are known
        self._var_shardings = None
        self._grad_shardings = None
        self._opt_shardings = None
        self._param_device_sh = None
        self._opt_device_sh = None
        self._params_offloaded = False
        self._opt_offloaded = False
        self._repl = None

    # -------------------------- placement ----------------------------- #

    def resolve_placement_abstract(self, variables, opt_state_shapes):
        """Compute NamedSharding trees for all state pytrees from concrete
        variables + *abstract* optimizer-state shapes, and return the
        variables device_put onto their placement (the one-time analogue of
        the reference's wrap ordering dance, stoke.py:306-324).  The optimizer
        state itself is then created directly sharded by
        :meth:`init_opt_state` — big models never hold a replicated opt state
        (the ZeRO-1 memory win, reference extensions.py:81-141)."""
        if self.rules is None:
            return variables
        params_sh = self.rules.param_shardings(variables["params"])
        other = {k: v for k, v in variables.items() if k != "params"}
        # non-param collections (BN stats etc.) follow the param rule; tiny
        # leaves stay replicated via min_weight_size
        other_sh = {k: self.rules.param_shardings(v) for k, v in other.items()}
        self._var_shardings = {"params": params_sh, **other_sh}
        self._grad_shardings = self.rules.grad_shardings(variables["params"])
        self._opt_shardings = self.rules.opt_shardings(opt_state_shapes)
        self._param_device_sh = params_sh
        self._opt_device_sh = self._opt_shardings
        # device-memory layout of the variables (== _var_shardings unless
        # param offload retargets the latter to pinned_host)
        self._var_device_shardings = self._var_shardings
        if self.offload_optimizer is not None:
            self._opt_shardings, self._opt_offloaded = self._offload_shardings(
                self._opt_shardings, self.offload_optimizer, "optimizer-state"
            )
        if self.offload_params is not None:
            # ZeRO-3 param offload (reference DeepspeedOffloadParamConfig,
            # configs.py:346-372): each chip's fsdp parameter shard lives in
            # host RAM between steps; the compiled steps copy it into HBM
            # (see _vars_to_compute) and write the update back to host via
            # out_shardings.  Non-param collections (BN stats etc.) stay on
            # device — small and touched every micro-batch.
            host_sh, self._params_offloaded = self._offload_shardings(
                params_sh, self.offload_params, "parameter"
            )
            self._var_shardings = {**self._var_shardings, "params": host_sh}
        self._repl = self.rules.replicated()
        return place_global_tree(variables, self._var_shardings)

    def _nonparam_device_shardings(self):
        """Device shardings of the mutable (non-param) collections — the
        ``updated`` output of the accum/fused steps (engine.py:105 makes every
        non-param collection mutable)."""
        return {
            k: v for k, v in self._var_device_shardings.items() if k != "params"
        }

    def _scaler_shardings(self):
        """Replicated placement for every scaler-state leaf.  The structure
        varies with the mode: per-loss scaling (``num_losses > 1``) carries
        an extra ``finite`` flag vector alongside scale/growth_count."""
        base = {"scale": self._repl, "growth_count": self._repl}
        if self.precision.scaled and self.precision_config.num_losses > 1:
            base["finite"] = self._repl
        return base

    def _offload_shardings(self, shardings, cfg, what: str):
        """Re-target a sharding tree to host memory
        (``memory_kind="pinned_host"``) — the ZeRO-offload equivalent
        (reference DeepspeedOffloadOptimizerConfig configs.py:309-343,
        DeepspeedOffloadParamConfig :346-372).  Returns ``(shardings,
        engaged)``; falls back to device placement (engaged=False) where the
        runtime cannot compile host-memory round-trips (e.g. the CPU
        simulator) when the config allows."""
        import warnings

        from jax.sharding import NamedSharding as _NS, PartitionSpec as _P

        def _to_host(sh):
            return _NS(sh.mesh, sh.spec, memory_kind="pinned_host")

        try:
            probe = jax.tree_util.tree_leaves(shardings)[0]
            # capability probe: COMPILE the pattern offload actually uses —
            # host input → device copy → compute → host output.  (A bare
            # device_put to pinned_host succeeds on runtimes that still
            # cannot compile host-memory outputs, e.g. the CPU simulator's
            # "Side-effect ops cannot be replicated".)  Replicated spec: we
            # only ask "does this runtime support host memory round-trips?".
            host_sh = _NS(probe.mesh, _P(), memory_kind="pinned_host")
            dev_sh = _NS(probe.mesh, _P())
            with jax.default_device(probe.mesh.devices.flat[0]):
                seed = place_global_tree(
                    np.zeros((1,), np.float32), host_sh
                )
                roundtrip = jax.jit(
                    lambda a: jax.device_put(a, dev_sh) + 1.0,
                    out_shardings=host_sh,
                )
                jax.block_until_ready(roundtrip(seed))
            return jax.tree_util.tree_map(_to_host, shardings), True
        except Exception:
            if cfg.fallback_to_device:
                warnings.warn(
                    f"Stoke -- {what} host offload unsupported on "
                    f"this runtime; keeping state on device"
                )
                return shardings, False
            raise

    def _vars_to_compute(self, variables):
        """Copy host-offloaded params into device memory inside a trace
        (XLA compiles this into a streamable host→HBM transfer).  Identity
        when param offload is off / fell back."""
        if not self._params_offloaded:
            return variables
        return {
            **variables,
            "params": jax.device_put(variables["params"], self._param_device_sh),
        }

    def _opt_to_compute(self, opt_state):
        """Same as :meth:`_vars_to_compute` for host-offloaded optimizer
        state (the update math runs in HBM; out_shardings write back)."""
        if not self._opt_offloaded:
            return opt_state
        return jax.device_put(opt_state, self._opt_device_sh)

    def init_grad_buffer(self, variables):
        """Zero accumulation buffer, sharded per the tier's grad rule
        (SDDP/FSDP: 1/N memory — the ZeRO-2 win, reference
        extensions.py:219-286)."""
        zeros = tree_zeros_like(variables["params"])
        if self._grad_shardings is not None:
            zeros = place_global_tree(zeros, self._grad_shardings)
        return zeros

    def init_comm_state(self, variables):
        """Carried gradient-transport state (stochastic-rounding rng +
        error-feedback residual, placed like the gradient buffer).  An
        empty dict when the transport is inactive — threading it through
        the compiled steps is then structurally free."""
        state = self.transport.init_state(variables["params"])
        if not state:
            return state
        if self._grad_shardings is not None:
            return place_global_tree(state, self._comm_state_shardings())
        return state

    def _comm_state_shardings(self):
        """out_shardings tree matching the comm state structure ({} when
        the transport is inactive)."""
        if self._grad_shardings is None or not self.transport.active:
            return {}
        return self.transport.state_shardings(self._grad_shardings, self._repl)

    def comm_bytes_per_step(self, variables) -> Optional[Dict[str, int]]:
        """Analytic per-device gradient bytes-on-wire of one optimizer
        step (telemetry: pre-quantization vs wire-format bytes)."""
        return self.transport.bytes_per_step(variables["params"])

    def init_opt_state(self, variables):
        """Optimizer-state init, created directly onto the tier's placement
        via ``out_shardings`` (never materialized replicated)."""
        if self._opt_shardings is not None:
            init = jax.jit(self.optimizer.init, out_shardings=self._opt_shardings)
            return init(variables["params"])
        return self.optimizer.init(variables["params"])

    # ----------------------- forward passes --------------------------- #

    def _maybe_remat(self, fn):
        if self.remat is None:
            return fn
        policy = getattr(jax.checkpoint_policies, self.remat.policy)
        return jax.checkpoint(fn, policy=policy, prevent_cse=self.remat.prevent_cse)

    def _run_forward_train(self, variables, rng, margs, mkwargs):
        cvars = {
            "params": self.precision.cast_compute(variables["params"]),
            **{k: v for k, v in variables.items() if k != "params"},
        }
        cargs = self.precision.cast_compute(margs)
        ckwargs = self.precision.cast_compute(mkwargs)
        out, updated = self.adapter.apply_train(cvars, rng, cargs, ckwargs)
        return self.precision.cast_output(out), updated

    def train_fwd(self, variables, rng, margs: tuple, mkwargs: dict):
        """Compiled train-mode forward for materializing DeferredOutputs.
        Uses the same rng-derivation as the fused step so dropout agrees."""
        key = ("fwd", jax.tree_util.tree_structure((margs, mkwargs)))
        if key not in self._fwd_cache:

            @jax.jit
            def _fwd(variables, rng, margs, mkwargs):
                variables = self._vars_to_compute(variables)
                sub = jax.random.split(rng)[1]
                out, _ = self._run_forward_train(variables, sub, margs, mkwargs)
                return out

            self._fwd_cache[key] = _fwd
        self._note_dispatch_shapes(key, margs, mkwargs)
        self.dispatch_count += 1
        return self._fwd_cache[key](variables, rng, margs, mkwargs)

    def eval_fwd(self, variables, margs: tuple, mkwargs: dict):
        key = ("eval", jax.tree_util.tree_structure((margs, mkwargs)))
        if key not in self._fwd_cache:

            @jax.jit
            def _efwd(variables, margs, mkwargs):
                variables = self._vars_to_compute(variables)
                cvars = {
                    "params": self.precision.cast_compute(variables["params"]),
                    **{k: v for k, v in variables.items() if k != "params"},
                }
                cargs = self.precision.cast_compute(margs)
                ckwargs = self.precision.cast_compute(mkwargs)
                out = self.adapter.apply_eval(cvars, cargs, ckwargs)
                return self.precision.cast_output(out)

            self._fwd_cache[key] = _efwd
        self._note_dispatch_shapes(key, margs, mkwargs)
        self.dispatch_count += 1
        return self._fwd_cache[key](variables, margs, mkwargs)

    #: per-program cap on remembered shape signatures: beyond this the
    #: membership test can no longer distinguish new shapes from evicted
    #: ones, so detection FREEZES for that program (no more counting —
    #: repeat-counting already-compiled shapes would be a permanent false
    #: alarm) and host memory stays bounded under pathological shape churn
    _MAX_SHAPE_SIGS = 1024

    @staticmethod
    def _shape_sig(batch_trees) -> tuple:
        """Input-shape signature of a dispatch's batch leaves — the key
        both the structural recompile detector and the attribution
        CostCard cache use to tell programs apart."""
        return tuple(
            (tuple(l.shape), str(getattr(l, "dtype", "")))
            for l in jax.tree_util.tree_leaves(batch_trees)
            if hasattr(l, "shape")
        )

    def _note_dispatch_shapes(self, key, *batch_trees) -> Optional[tuple]:
        """Telemetry hook: record the input-shape signature of a dispatch.
        First signature per program = warm-up compile; any LATER new
        signature means XLA silently recompiles the warm program (ragged
        batch / drifting pad length) — reported to THIS engine's
        ``CompileTracker`` (assigned by the facade; no bookkeeping at all
        when telemetry is off).  Returns the signature so the attribution
        hook (:meth:`_note_cost`) reuses it instead of recomputing it on
        the dispatch hot path; None when nobody needs one."""
        tracker = self._compile_tracker
        if (
            tracker is None
            and self._attribution is None
            and self._compile_cache is None
            and self._memory is None
        ):
            return None
        sig = self._shape_sig(batch_trees)
        if tracker is None:
            return sig
        seen = self._shape_sigs.setdefault(key, set())
        if len(seen) >= self._MAX_SHAPE_SIGS or sig in seen:
            return sig
        if seen:
            tracker.note_recompile()
        seen.add(sig)
        return sig

    def _note_cost(self, program: str, key, fn, args: tuple, steps: int,
                   sig: Optional[tuple]) -> None:
        """Attribution hook (ISSUE 4): account this dispatch's analytic
        cost.  First call per (program key, shape signature) runs one XLA
        cost analysis on ``fn`` at ``args``; every call adds the cached
        card's FLOPs/bytes to the attribution counters.  ``sig`` is the
        signature :meth:`_note_dispatch_shapes` already computed for this
        dispatch.  No-op without an ``AttributionConfig`` (the facade
        never assigns the cache)."""
        attr = self._attribution
        if attr is None:
            return
        attr.note_dispatch((key, sig or ()), program, fn, args, steps)

    def _aot_call(self, program: str, key, sig: Optional[tuple], fn,
                  args: tuple):
        """Compile-cache hook (ISSUE 6): resolve the callable that will
        run this dispatch.  ``fn`` itself without a cache; with one, the
        first dispatch per (program key, shape signature) goes through
        the cache's HLO-keyed program ledger — which books the warm-start
        hit (the persistent XLA cache serves the impending backend
        compile) or records the cold cost — and every later dispatch is
        ``fn`` untouched.  Dispatch semantics (donation, async, numerics)
        are ALWAYS plain ``jax.jit``.

        Also the fault injector's pre-dispatch hook (ISSUE 7): with a
        chaos spec armed, ``wedge_at_step`` stalls the first dispatch after
        its step here — the deterministic stand-in for a wedged collective
        the hang watchdog exists to catch.  And the program-audit
        ledger's recording point (ISSUE 15): one abstract spec per
        (program, key, sig), first dispatch only."""
        if self._chaos is not None:
            self._chaos.on_dispatch(program)
        self._note_audit(program, key, sig, fn, args)
        if self._memory is not None:
            self._memory.note_program(program, fn, args, (key, sig))
        cache = self._compile_cache
        if cache is None:
            return fn
        return cache.executable(program, (key, sig), fn, args)

    #: bound on remembered audit specs (one per program signature; a
    #: shape-churning run stops recording, never errors)
    _MAX_AUDIT_SPECS = 64

    def _jit_program(self, program: str, fn, *, donate: Tuple[int, ...] = (),
                     out_shardings=None):
        """``jax.jit`` a step program AND record its declared donations
        under the program's audit name — stated once, here, so the
        ISSUE 15 donation-integrity check can never drift from what the
        jit actually received."""
        self._program_donations[program] = tuple(donate)
        if out_shardings is not None:
            return jax.jit(fn, out_shardings=out_shardings,
                           donate_argnums=donate)
        return jax.jit(fn, donate_argnums=donate)

    def _note_audit(self, program: str, key, sig, fn, args: tuple) -> None:
        """Record one abstract ProgramSpec per (program, key, sig) for
        the ISSUE 15 auditor — shapes/dtypes/shardings only, taken while
        the args are still live (pre-donation)."""
        memo = (program, key, sig)
        if memo in self._audit_seen:
            return
        if len(self._audit_specs) >= self._MAX_AUDIT_SPECS:
            self._audit_truncated = True
            return
        self._audit_seen.add(memo)
        from stoke_tpu.analysis.program import ProgramSpec, abstractify_args

        avals, weak = abstractify_args(args)
        self._audit_specs.append(
            ProgramSpec(
                program=program,
                fn=fn,
                abstract_args=avals,
                donate_argnums=self._program_donations.get(program, ()),
                weak_leaves=weak,
                source="engine",
            )
        )

    def audit_specs(self) -> list:
        """The recorded program specs (ISSUE 15; ``Stoke.audit()`` is
        the consumer)."""
        return list(self._audit_specs)

    def shape_sig_counts(self) -> Dict[str, int]:
        """Distinct input-shape signatures seen per program key — the
        auditor's recompile-churn ledger.  Keyed by the program's
        human-readable name (the first key element)."""
        out: Dict[str, int] = {}
        for key, seen in self._shape_sigs.items():
            name = key[0] if isinstance(key, tuple) and key else str(key)
            name = str(name)
            out[name] = max(out.get(name, 0), len(seen))
        return out

    # -------------------------- fused micro-step ----------------------- #

    def accum_step(
        self,
        variables,
        grad_buf,
        scaler_state,
        rng,
        margs: tuple,
        mkwargs: dict,
        loss_args_flat: list,
        loss_treedef,
        deferred_info: Tuple[Tuple[int, Tuple], ...],
        training: bool,
    ):
        """One compiled micro-step: forward + loss + grad + buffer add.

        ``loss_args_flat``/``loss_treedef`` are the flattened (args, kwargs)
        of the user's ``loss()`` call with DeferredOutput leaves removed;
        ``deferred_info`` records (flat_index, extraction_path) for each
        removed leaf so the real forward output is substituted inside the
        trace.  Returns (loss_tree, updated_nonparam_vars, new_grad_buf,
        new_scaler_state, new_rng) — all device-resident; nothing syncs to
        host (SURVEY.md §3.2 observation (a)).  The scaler is pass-through
        except in per-loss mode (``PrecisionConfig.num_losses > 1``).
        """
        struct_key = (
            "accum",
            jax.tree_util.tree_structure((margs, mkwargs)),
            loss_treedef,
            deferred_info,
            training,
        )
        if struct_key not in self._accum_cache:
            self._accum_cache[struct_key] = self._build_accum(
                loss_treedef, deferred_info, training
            )
        sig = self._note_dispatch_shapes(
            struct_key, margs, mkwargs, loss_args_flat
        )
        # micro-step: contributes FLOPs but completes no optimizer step
        self._note_cost(
            "accum", struct_key, self._accum_cache[struct_key],
            (variables, grad_buf, scaler_state, rng, margs, mkwargs,
             loss_args_flat),
            0, sig,
        )
        call = self._aot_call(
            "accum", struct_key, sig, self._accum_cache[struct_key],
            (variables, grad_buf, scaler_state, rng, margs, mkwargs,
             loss_args_flat),
        )
        self.dispatch_count += 1
        with trace_span("stoke/accum", track="step"):
            return call(
                variables, grad_buf, scaler_state, rng, margs, mkwargs,
                loss_args_flat,
            )

    def _accum_core(self, loss_treedef, deferred_info, training):
        """Unjitted micro-step core: forward + loss + grad + buffer add.
        Shared by the lazy 4-call path and the fused train_step path.

        Returns ``(report, updated_nonparam, new_buf, new_scaler, new_rng)``.
        The scaler is pass-through except in per-loss mode (``num_losses >
        1``), where each micro-step ANDs per-loss backward finiteness into
        the carried flag vector (reference: Apex updates its per-loss
        scalers inside each ``scale_loss`` context, fp16.py:545-579)."""
        inv_scale_accum = 1.0 / self.grad_accum if training else 1.0
        scaled = self.precision.scaled
        per_loss = scaled and self.precision_config.num_losses > 1
        n_scales = self.precision_config.num_losses

        def _loss_from_out(out, loss_args_flat):
            flat = list(loss_args_flat)
            # re-insert deferred leaves (extracted views of the forward out)
            for idx, path in deferred_info:
                flat.insert(idx, apply_path(out, path))
            largs, lkwargs = jax.tree_util.tree_unflatten(loss_treedef, flat)
            return self.loss_fn(*largs, **lkwargs)

        def _step(variables, grad_buf, scaler_state, rng, margs, mkwargs, larr):
            # host-offloaded params → HBM copy OUTSIDE the grad closure, so
            # grad cotangents stay in device memory (a transfer inside the
            # closure would transpose to a host-memory cotangent and bounce
            # the gradients host→device for the buffer add)
            variables = self._vars_to_compute(variables)
            new_rng, sub = jax.random.split(rng)
            # per-loss mode scales the VJP seeds instead of the objective
            scale = (
                scaler_state["scale"]
                if scaled and not per_loss
                else jnp.float32(1.0)
            )

            def _forward_comps(params):
                """Shared forward + per-leaf weighted loss components.

                Returns ``(comps, report, updated)``: ``comps`` is one f32
                scalar per loss leaf (weights applied, model-internal aux
                losses folded into the FIRST component — they have no
                scaler/weight slot of their own), ``report`` the UNweighted
                per-loss values the user sees.  ``lf`` consumes the sum
                (single backward), ``lf_vec`` the stacked vector (one
                scale-seeded backward per loss) — sharing this body is what
                keeps the two objectives from drifting.
                """
                vars_in = {**variables, "params": params}
                fwd = self._maybe_remat(
                    lambda v: self._run_forward_train(v, sub, margs, mkwargs)
                )
                out, updated = fwd(vars_in)
                loss_result = _loss_from_out(out, larr)
                leaves, inner_def = jax.tree_util.tree_flatten(loss_result)
                if self.loss_weights is not None:
                    # weighted multi-loss: the objective is Σ wᵢ·lossᵢ.
                    # Gradients are linear, so one backward of the weighted
                    # sum ≡ the reference's per-loss backward passes with
                    # weights (fp16.py:545-579, stoke.py:891-902).
                    try:
                        weighted = jax.tree_util.tree_map(
                            lambda w, l: jnp.float32(w)
                            * jnp.asarray(l, jnp.float32).sum(),
                            self.loss_weights,
                            loss_result,
                        )
                    except ValueError as e:
                        raise ValueError(
                            "Stoke -- loss_weights structure must match the "
                            "loss() return structure"
                        ) from e
                    comps = jax.tree_util.tree_leaves(weighted)
                else:
                    comps = [
                        jnp.asarray(l, jnp.float32).sum() for l in leaves
                    ]
                # model-internal auxiliary losses (e.g. the MoE router's
                # load-balancing term) arrive sown into the "losses"
                # collection (models/moe.py); they join the objective with
                # the configured weight but are NOT part of the user's loss
                # report (observable via the facade's state instead)
                if self.aux_loss_weight and "losses" in updated:
                    aux_leaves = jax.tree_util.tree_leaves(updated["losses"])
                    if aux_leaves:
                        comps[0] = comps[0] + jnp.float32(
                            self.aux_loss_weight
                        ) * sum(
                            jnp.asarray(a, jnp.float32).sum()
                            for a in aux_leaves
                        )
                # reference divides the training loss by grad_accum at
                # loss() time (stoke.py:901-911).  Reported per-loss values
                # stay UNweighted.
                report = jax.tree_util.tree_unflatten(
                    inner_def, [l * inv_scale_accum for l in leaves]
                )
                return comps, report, updated

            def lf(params):
                comps, report, updated = _forward_comps(params)
                # fp16 single-scaler mode additionally multiplies by the
                # dynamic scale; per-loss overflow isolation is subsumed by
                # the single scaler here.
                objective = sum(comps) * inv_scale_accum * scale
                return objective, (report, updated)

            def lf_vec(params):
                # per-loss objective VECTOR: components stay separate so
                # each loss's backward can be seeded with its own scale
                comps, report, updated = _forward_comps(params)
                if len(comps) != n_scales:
                    raise ValueError(
                        f"Stoke -- PrecisionConfig.num_losses={n_scales} "
                        f"but loss() returned {len(comps)} loss leaves — "
                        f"per-loss scalers need one scale per loss"
                    )
                return (
                    jnp.stack(comps) * inv_scale_accum,
                    (report, updated),
                )

            if training and per_loss:
                # reference per-loss scalers (fp16.py:545-579): one forward,
                # one backward per loss.  jax.vjp shares the forward; each
                # backward is seeded with that loss's scale (protecting fp16
                # cotangents from underflow), checked for overflow, then
                # unscaled straight into the fp32 accumulation buffer —
                # which therefore holds UNSCALED gradients (apply's unscale
                # is the identity in this mode).
                scales = scaler_state["scale"]
                _, vjp_fn, (report, updated) = jax.vjp(
                    lf_vec, variables["params"], has_aux=True
                )
                new_buf = grad_buf
                new_finite = scaler_state["finite"]
                for i in range(n_scales):
                    seed = (
                        jnp.zeros((n_scales,), jnp.float32)
                        .at[i].set(scales[i])
                    )
                    (g_i,) = vjp_fn(seed)
                    new_finite = new_finite.at[i].set(
                        new_finite[i] & tree_finite(g_i)
                    )
                    inv_i = 1.0 / scales[i]
                    new_buf = jax.tree_util.tree_map(
                        lambda b, g: b + (g * inv_i).astype(b.dtype),
                        new_buf,
                        g_i,
                    )
                new_scaler = {**scaler_state, "finite": new_finite}
            elif training:
                grads, (report, updated) = jax.grad(lf, has_aux=True)(
                    variables["params"]
                )
                new_buf = jax.tree_util.tree_map(
                    lambda b, g: b + g.astype(b.dtype), grad_buf, grads
                )
                new_scaler = scaler_state
            else:
                _, (report, updated) = lf(variables["params"])
                new_buf = grad_buf
                new_scaler = scaler_state
            return report, updated, new_buf, new_scaler, new_rng

        return _step

    def _build_accum(self, loss_treedef, deferred_info, training):
        _step = self._accum_core(loss_treedef, deferred_info, training)
        if self.rules is not None:
            # Pin state outputs to the tier's placement so step-to-step
            # placement is deterministic (GSPMD would otherwise be free to
            # drift, changing collective schedules between steps).
            repl = self._repl
            out_sh = (
                None,  # loss report: let XLA keep it replicated (scalars)
                # updated non-param collections (BN stats etc.): pin to the
                # tier placement — left unconstrained, GSPMD shards them to
                # match the data-sharded activations they were reduced from,
                # which then defeats buffer donation (and forces a reshard)
                # at the apply boundary where the tier placement is required
                self._nonparam_device_shardings(),
                self._grad_shardings,
                self._scaler_shardings(),
                repl,  # rng
            )
            return self._jit_program("accum", _step, out_shardings=out_sh)
        return self._jit_program("accum", _step)

    # ----------------------- scan window step --------------------------- #

    def window_step(
        self,
        variables,
        opt_state,
        grad_buf,
        scaler_state,
        comm_state,
        rng,
        margs_stacked: tuple,
        mkwargs_stacked: dict,
        loss_args_flat_stacked: list,
        loss_treedef,
        deferred_info: Tuple[Tuple[int, Tuple], ...],
    ):
        """A WHOLE accumulation window in one compiled dispatch:
        ``lax.scan`` over the k stacked micro-batches (grad accumulation as
        compiler-visible control flow — SURVEY.md §3.2 observation (b)),
        then the fused optimizer apply.  Semantically identical to k
        ``train_step`` calls; one dispatch instead of k.

        Stacked args carry the micro dimension on axis 0 (leaf shape
        [k, micro_batch, ...]).  Returns (reports_stacked, variables,
        opt_state, grad_buf, scaler_state, comm_state, rng, sentinels,
        numerics, finite) — ``sentinels`` is the health diagnostics vector
        and ``numerics`` the per-group stats matrix (each None when its
        feature is off).
        """
        key = (
            "window",
            jax.tree_util.tree_structure((margs_stacked, mkwargs_stacked)),
            loss_treedef,
            deferred_info,
        )
        if key not in self._accum_cache:
            self._accum_cache[key] = self._build_window(loss_treedef, deferred_info)
        sig = self._note_dispatch_shapes(
            key, margs_stacked, mkwargs_stacked, loss_args_flat_stacked
        )
        self._note_cost(
            "window", key, self._accum_cache[key],
            (variables, opt_state, grad_buf, scaler_state, comm_state, rng,
             margs_stacked, mkwargs_stacked, loss_args_flat_stacked),
            1, sig,
        )
        call = self._aot_call(
            "window", key, sig, self._accum_cache[key],
            (variables, opt_state, grad_buf, scaler_state, comm_state, rng,
             margs_stacked, mkwargs_stacked, loss_args_flat_stacked),
        )
        self.dispatch_count += 1
        with trace_span("stoke/dispatch", track="step"):
            return call(
                variables, opt_state, grad_buf, scaler_state, comm_state,
                rng, margs_stacked, mkwargs_stacked, loss_args_flat_stacked,
            )

    def _report_loss(self, report):
        """Boundary-loss scalar for the health sentinels (traced): sum over
        loss leaves of each leaf's mean (collapsing any stacked micro axis),
        times ``grad_accum`` — undivided micro-loss units, matching the
        facade's ``step_loss`` tracking on every path."""
        total = jnp.float32(0.0)
        for l in jax.tree_util.tree_leaves(report):
            total = total + jnp.asarray(l, jnp.float32).mean()
        return total * jnp.float32(self.grad_accum)

    def _window_core(self, loss_treedef, deferred_info):
        """Unjitted whole-window core: inner ``lax.scan`` over the stacked
        micro-batches + the fused optimizer apply.  Shared by
        ``_build_window`` (jitted directly) and ``_build_multi`` (scanned
        over n windows) so the two APIs cannot diverge."""
        accum = self._accum_core(loss_treedef, deferred_info, training=True)
        apply_core = self._apply_core()

        def _window(variables, opt_state, grad_buf, scaler_state, comm_state,
                    rng, margs_s, mkwargs_s, larr_s):
            # host-offloaded params → HBM ONCE, outside the scan (the accum
            # core's own transfer is then a no-op on already-device params)
            variables = self._vars_to_compute(variables)
            params = variables["params"]
            nonparam0 = {k: v for k, v in variables.items() if k != "params"}

            def body(carry, xs):
                nonparam, buf, scaler, rng = carry
                margs, mkwargs, larr = xs
                report, updated, buf, scaler, rng = accum(
                    {"params": params, **nonparam}, buf, scaler, rng,
                    margs, mkwargs, larr,
                )
                return ({**nonparam, **updated}, buf, scaler, rng), report

            (nonparam_f, new_buf, scaler_mid, new_rng), reports = jax.lax.scan(
                body,
                (nonparam0, grad_buf, scaler_state, rng),
                (margs_s, mkwargs_s, larr_s),
            )
            merged = {"params": params, **nonparam_f}
            loss_val = (
                self._report_loss(reports) if self.sentinels_enabled else None
            )
            (new_vars, new_opt, zero_buf, new_scaler, new_comm, sentinels,
             numerics, finite) = apply_core(
                merged, opt_state, new_buf, scaler_mid, comm_state, loss_val
            )
            return (reports, new_vars, new_opt, zero_buf, new_scaler,
                    new_comm, new_rng, sentinels, numerics, finite)

        return _window

    def _build_window(self, loss_treedef, deferred_info):
        _window = self._window_core(loss_treedef, deferred_info)

        if self.rules is not None:
            repl = self._repl
            out_sh = (
                None,
                self._var_shardings,
                self._opt_shardings,
                self._grad_shardings,
                self._scaler_shardings(),
                self._comm_state_shardings(),
                repl,  # rng
                self._sentinel_shardings(),
                self._numerics_shardings(),
                repl,  # finite
            )
            return self._jit_program(
                "window", _window, out_shardings=out_sh,
                donate=(0, 1, 2, 4),
            )
        return self._jit_program("window", _window, donate=(0, 1, 2, 4))

    # ----------------------- multi-step scan ---------------------------- #

    def multi_step(
        self,
        variables,
        opt_state,
        grad_buf,
        scaler_state,
        comm_state,
        rng,
        margs_stacked: tuple,
        mkwargs_stacked: dict,
        loss_args_flat_stacked: list,
        loss_treedef,
        deferred_info: Tuple[Tuple[int, Tuple], ...],
    ):
        """N COMPLETE optimizer steps in one compiled dispatch: an outer
        ``lax.scan`` over steps, each iterating its accumulation window and
        the fused apply.  One XLA program drives a whole training segment —
        host dispatch (and, on remote-device links, per-dispatch round-trip
        latency) is amortized over ``n × grad_accum`` micro-batches.  No
        reference equivalent (the reference's hot loop is eager,
        stoke.py:853-1040).

        Stacked args carry [n_steps, grad_accum, micro_batch, ...] leaves.
        Returns (reports [n, k, ...], variables, opt_state, grad_buf,
        scaler_state, comm_state, rng, sentinels [n, S] (None when off),
        numerics [n, G, S'] (None when off), n_nonfinite_steps).
        """
        key = (
            "multi",
            jax.tree_util.tree_structure((margs_stacked, mkwargs_stacked)),
            loss_treedef,
            deferred_info,
        )
        if key not in self._accum_cache:
            self._accum_cache[key] = self._build_multi(loss_treedef, deferred_info)
        sig = self._note_dispatch_shapes(
            key, margs_stacked, mkwargs_stacked, loss_args_flat_stacked
        )
        if self._attribution is not None:
            # one dispatch covers n complete optimizer steps
            n_steps = next(
                (
                    l.shape[0]
                    for l in jax.tree_util.tree_leaves(
                        (margs_stacked, mkwargs_stacked,
                         loss_args_flat_stacked)
                    )
                    if hasattr(l, "shape") and l.shape
                ),
                1,
            )
            self._note_cost(
                "multi", key, self._accum_cache[key],
                (variables, opt_state, grad_buf, scaler_state, comm_state,
                 rng, margs_stacked, mkwargs_stacked,
                 loss_args_flat_stacked),
                int(n_steps), sig,
            )
        call = self._aot_call(
            "multi", key, sig, self._accum_cache[key],
            (variables, opt_state, grad_buf, scaler_state, comm_state, rng,
             margs_stacked, mkwargs_stacked, loss_args_flat_stacked),
        )
        self.dispatch_count += 1
        with trace_span("stoke/dispatch", track="step"):
            return call(
                variables, opt_state, grad_buf, scaler_state, comm_state,
                rng, margs_stacked, mkwargs_stacked, loss_args_flat_stacked,
            )

    def _build_multi(self, loss_treedef, deferred_info):
        window = self._window_core(loss_treedef, deferred_info)

        def _multi(variables, opt_state, grad_buf, scaler_state, comm_state,
                   rng, margs_s, mkwargs_s, larr_s):
            # offloaded state → HBM ONCE, outside both scans (the cores'
            # internal transfers are no-ops on already-device state)
            variables = self._vars_to_compute(variables)
            opt_state = self._opt_to_compute(opt_state)

            def step_body(carry, xs):
                (variables, opt_state, buf, scaler_state, comm_state, rng,
                 skipped) = carry
                margs, mkwargs, larr = xs  # [k, ...] micro-batches
                (reports, new_vars, new_opt, zero_buf, new_scaler, new_comm,
                 new_rng, sentinels, numerics, finite) = window(
                    variables, opt_state, buf, scaler_state, comm_state, rng,
                    margs, mkwargs, larr,
                )
                skipped = skipped + (1.0 - finite.astype(jnp.float32))
                return (
                    (new_vars, new_opt, zero_buf, new_scaler, new_comm,
                     new_rng, skipped),
                    (reports, sentinels, numerics),
                )

            ((vars_f, opt_f, buf_f, scaler_f, comm_f, rng_f, skipped),
             (reports, sentinels_s, numerics_s)) = jax.lax.scan(
                step_body,
                (variables, opt_state, grad_buf, scaler_state, comm_state,
                 rng, jnp.float32(0.0)),
                (margs_s, mkwargs_s, larr_s),
            )
            return (reports, vars_f, opt_f, buf_f, scaler_f, comm_f, rng_f,
                    sentinels_s, numerics_s, skipped)

        if self.rules is not None:
            repl = self._repl
            out_sh = (
                None,
                self._var_shardings,
                self._opt_shardings,
                self._grad_shardings,
                self._scaler_shardings(),
                self._comm_state_shardings(),
                repl,  # rng
                self._sentinel_shardings(),  # stacked sentinel rows
                self._numerics_shardings(),  # stacked group-stats matrices
                repl,  # skipped count
            )
            return self._jit_program(
                "multi", _multi, out_shardings=out_sh, donate=(0, 1, 2, 4)
            )
        return self._jit_program("multi", _multi, donate=(0, 1, 2, 4))

    # ---------------------------- apply step --------------------------- #

    def apply_step(self, variables, opt_state, grad_buf, scaler_state,
                   comm_state, loss_val=None):
        """Compiled optimizer application: unscale → gradient transport →
        finite-check → clip → update → zero buffer → scaler update
        (reference step() path, stoke.py:990-1040 + fp16.py:788-806).

        ``loss_val``: boundary loss scalar for the health sentinels (None
        — an empty jit input — when sentinels are off).  Returns extra
        sentinel-vector and per-group numerics-matrix slots before
        ``finite`` (each None when its feature is off)."""
        if self._apply_fn is None:
            self._apply_fn = self._build_apply()
        self._note_cost(
            "apply", "apply", self._apply_fn,
            (variables, opt_state, grad_buf, scaler_state, comm_state,
             loss_val),
            1, (),
        )
        call = self._aot_call(
            "apply", "apply", (), self._apply_fn,
            (variables, opt_state, grad_buf, scaler_state, comm_state,
             loss_val),
        )
        self.dispatch_count += 1
        with trace_span("stoke/step", track="step"):
            return call(
                variables, opt_state, grad_buf, scaler_state, comm_state,
                loss_val,
            )

    def _apply_core(self):
        """Unjitted apply core, shared by step() and the fused train_step."""
        scaled = self.precision.scaled
        cfg = self.precision_config
        grad_clip = self.grad_clip
        optimizer = self.optimizer
        transport = self.transport
        sentinels_on = self.sentinels_enabled
        numerics_on = self.numerics_enabled

        def _apply(variables, opt_state, grad_buf, scaler_state, comm_state,
                   loss_val=None):
            # host-offloaded state → HBM for the (bandwidth-bound) update;
            # out_shardings write new params / opt state back to host
            variables = self._vars_to_compute(variables)
            opt_state = self._opt_to_compute(opt_state)
            params = variables["params"]
            per_loss = scaled and cfg.num_losses > 1
            if per_loss:
                # per-loss mode unscales inside the accumulate step (each
                # backward by its own scale); the buffer is already unscaled
                inv = jnp.float32(1.0)
            else:
                inv = (
                    1.0 / scaler_state["scale"] if scaled else jnp.float32(1.0)
                )
            grads = jax.tree_util.tree_map(lambda g: g * inv, grad_buf)
            # gradient transport (ISSUE 2): quantized exchange + error
            # feedback on the UNSCALED, whole-window gradients — once per
            # optimizer step, never per micro-step.  Inactive transport
            # (no CommConfig / dtype="fp32") returns grads and the empty
            # state untouched: the compiled program is unchanged.
            grads, new_comm = transport.apply(grads, comm_state)
            # health sentinels AND the per-layer numerics matrix read the
            # unscaled post-transport gradients (pre-clip — a clipped-away
            # spike must still be visible; one shared tap point keeps the
            # per-group sums recombining exactly to the sentinel norm)
            health_grads = grads if (sentinels_on or numerics_on) else None
            finite = tree_finite(grads) if scaled else jnp.asarray(True)
            if per_loss:
                # any loss overflowing anywhere in the window skips the step
                # (reference: amp skips optimizer.step on overflow)
                finite = finite & jnp.all(scaler_state["finite"])
            grads = clip_gradients(grads, grad_clip)

            def do_update(_):
                updates, new_opt = optimizer.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                return new_params, new_opt

            def skip_update(_):
                return params, opt_state

            new_params, new_opt = jax.lax.cond(finite, do_update, skip_update, None)
            if per_loss:
                # vectorized update driven by the per-loss flags, which then
                # reset for the next accumulation window
                upd = _scaler_update(
                    {
                        "scale": scaler_state["scale"],
                        "growth_count": scaler_state["growth_count"],
                    },
                    scaler_state["finite"],
                    cfg,
                )
                new_scaler = {
                    **upd,
                    "finite": jnp.ones_like(scaler_state["finite"]),
                }
            elif scaled:
                new_scaler = _scaler_update(scaler_state, finite, cfg)
            else:
                new_scaler = scaler_state
            new_vars = {**variables, "params": new_params}
            zero_buf = tree_zeros_like(grad_buf)
            # sentinel vector (ISSUE 3): a handful of scalar reductions
            # fused into THIS program — None (empty pytree) when off, so
            # the default-off program is bit-identical
            sentinels = (
                compute_sentinels(
                    loss_val, health_grads, new_params, params, finite,
                    new_comm,
                )
                if sentinels_on
                else None
            )
            # per-layer numerics matrix (ISSUE 12): per-module-group raw
            # sums fused into THIS program — None (empty pytree) when off,
            # so the default-off program is bit-identical
            numerics = (
                compute_group_stats(health_grads, new_params, params)
                if numerics_on
                else None
            )
            return (new_vars, new_opt, zero_buf, new_scaler, new_comm,
                    sentinels, numerics, finite)

        return _apply

    def _sentinel_shardings(self):
        """out_shardings slot for the sentinel vector: replicated when on,
        None (matching the empty pytree) when off."""
        return self._repl if self.sentinels_enabled else None

    def _numerics_shardings(self):
        """out_shardings slot for the per-group numerics matrix (ISSUE
        12): replicated when on, None (empty pytree) when off."""
        return self._repl if self.numerics_enabled else None

    def _build_apply(self):
        _apply = self._apply_core()
        if self.rules is not None:
            out_sh = (
                self._var_shardings,
                self._opt_shardings,
                self._grad_shardings,
                self._scaler_shardings(),
                self._comm_state_shardings(),
                self._sentinel_shardings(),
                self._numerics_shardings(),
                self._repl,
            )
            return self._jit_program(
                "apply", _apply, out_shardings=out_sh, donate=(0, 1, 2, 4)
            )
        return self._jit_program("apply", _apply, donate=(0, 1, 2, 4))

    # ------------------------ fused train step -------------------------- #

    def fused_step(
        self,
        variables,
        opt_state,
        grad_buf,
        scaler_state,
        comm_state,
        rng,
        margs: tuple,
        mkwargs: dict,
        loss_args_flat: list,
        loss_treedef,
        deferred_info: Tuple[Tuple[int, Tuple], ...],
        do_apply: bool,
    ):
        """ONE compiled dispatch for a whole micro-step — and, at the
        accumulation boundary (``do_apply``), the optimizer apply fused in.

        This is the TPU-idiomatic fast path behind ``Stoke.train_step``: with
        ``grad_accum == 1`` an entire optimizer step (forward + loss + grad +
        clip + update) is a single XLA program — no reference equivalent (the
        reference's eager hot loop is stoke.py:853-1040).  The 4-call API
        compiles the same math split across two dispatches.

        Returns (report, updated_nonparam_vars, variables, opt_state,
        grad_buf, scaler_state, comm_state, rng, sentinels, numerics,
        finite) — ``sentinels``/``numerics`` are the health diagnostics
        vector and per-group stats matrix at apply boundaries (None
        off-boundary or when the feature is off).
        """
        key = (
            "fused",
            jax.tree_util.tree_structure((margs, mkwargs)),
            loss_treedef,
            deferred_info,
            bool(do_apply),
        )
        if key not in self._accum_cache:
            self._accum_cache[key] = self._build_fused(
                loss_treedef, deferred_info, bool(do_apply)
            )
        sig = self._note_dispatch_shapes(key, margs, mkwargs, loss_args_flat)
        self.dispatch_count += 1
        if do_apply:
            self._note_cost(
                "fused", key, self._accum_cache[key],
                (variables, opt_state, grad_buf, scaler_state, comm_state,
                 rng, margs, mkwargs, loss_args_flat),
                1, sig,
            )
            call = self._aot_call(
                "fused", key, sig, self._accum_cache[key],
                (variables, opt_state, grad_buf, scaler_state, comm_state,
                 rng, margs, mkwargs, loss_args_flat),
            )
            with trace_span("stoke/dispatch", track="step"):
                return call(
                    variables, opt_state, grad_buf, scaler_state, comm_state,
                    rng, margs, mkwargs, loss_args_flat,
                )
        # non-boundary micro-steps never touch the optimizer state or the
        # transport state (quantization is once-per-step): both stay
        # wherever they live and the caller's references are echoed
        # untouched
        self._note_cost(
            "fused_nb", key, self._accum_cache[key],
            (variables, grad_buf, scaler_state, rng, margs, mkwargs,
             loss_args_flat),
            0, sig,
        )
        call = self._aot_call(
            "fused_nb", key, sig, self._accum_cache[key],
            (variables, grad_buf, scaler_state, rng, margs, mkwargs,
             loss_args_flat),
        )
        with trace_span("stoke/dispatch", track="step"):
            (report, updated, new_vars, new_buf, new_scaler, new_rng,
             finite) = call(
                variables, grad_buf, scaler_state, rng, margs, mkwargs,
                loss_args_flat,
            )
        return (report, updated, new_vars, opt_state, new_buf, new_scaler,
                comm_state, new_rng, None, None, finite)

    def _build_fused(self, loss_treedef, deferred_info, do_apply):
        accum = self._accum_core(loss_treedef, deferred_info, training=True)
        apply_core = self._apply_core()

        if do_apply:

            def _fused(variables, opt_state, grad_buf, scaler_state,
                       comm_state, rng, margs, mkwargs, larr):
                # host-offloaded params → HBM ONCE for both accum and apply
                # (the cores' own transfers become no-ops on already-device
                # params)
                variables = self._vars_to_compute(variables)
                report, updated, new_buf, scaler_mid, new_rng = accum(
                    variables, grad_buf, scaler_state, rng, margs, mkwargs,
                    larr
                )
                merged = {**variables, **updated}
                loss_val = (
                    self._report_loss(report)
                    if self.sentinels_enabled
                    else None
                )
                (new_vars, new_opt, zero_buf, new_scaler, new_comm,
                 sentinels, numerics, finite) = apply_core(
                    merged, opt_state, new_buf, scaler_mid, comm_state,
                    loss_val,
                )
                return (report, updated, new_vars, new_opt, zero_buf,
                        new_scaler, new_comm, new_rng, sentinels, numerics,
                        finite)

            if self.rules is not None:
                repl = self._repl
                out_sh = (
                    None,  # report
                    self._nonparam_device_shardings(),  # updated collections
                    self._var_shardings,
                    self._opt_shardings,
                    self._grad_shardings,
                    self._scaler_shardings(),
                    self._comm_state_shardings(),
                    repl,  # rng
                    self._sentinel_shardings(),
                    self._numerics_shardings(),
                    repl,  # finite
                )
                return self._jit_program(
                    "fused", _fused, out_shardings=out_sh,
                    donate=(0, 1, 2, 4),
                )
            return self._jit_program("fused", _fused, donate=(0, 1, 2, 4))

        def _fused_nb(variables, grad_buf, scaler_state, rng, margs, mkwargs,
                      larr):
            variables = self._vars_to_compute(variables)
            report, updated, new_buf, new_scaler, new_rng = accum(
                variables, grad_buf, scaler_state, rng, margs, mkwargs, larr
            )
            merged = {**variables, **updated}
            return (report, updated, merged, new_buf, new_scaler, new_rng,
                    jnp.asarray(True))

        if self.rules is not None:
            repl = self._repl
            out_sh = (
                None,  # report
                self._nonparam_device_shardings(),  # updated collections
                # non-boundary micro-steps leave params in device memory:
                # writing the UNCHANGED params back to pinned_host (and in
                # again next micro-step) would be a pure host<->HBM round
                # trip; only the boundary step persists to the offload tier
                self._var_device_shardings,
                self._grad_shardings,
                self._scaler_shardings(),
                repl,  # rng
                repl,  # finite
            )
            return self._jit_program(
                "fused_nb", _fused_nb, out_shardings=out_sh, donate=(0, 1)
            )
        return self._jit_program("fused_nb", _fused_nb, donate=(0, 1))

    # --------------------------- loss-only ----------------------------- #

    def loss_eval(self, loss_args_flat, loss_treedef):
        """Compiled loss-only evaluation (eval mode; outputs are real arrays
        so no substitution is needed)."""
        key = ("loss", loss_treedef)
        if key not in self._loss_cache:

            @jax.jit
            def _loss(flat):
                largs, lkwargs = jax.tree_util.tree_unflatten(loss_treedef, flat)
                return self.loss_fn(*largs, **lkwargs)

            self._loss_cache[key] = _loss
        self.dispatch_count += 1
        return self._loss_cache[key](loss_args_flat)

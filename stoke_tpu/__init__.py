"""stoke_tpu: a TPU-native declarative training framework.

Brand-new JAX/XLA/pjit implementation of the capabilities of the reference
``stoke`` library (facade + status validation + one SPMD engine replacing the
DDP/Horovod/DeepSpeed/fairscale/AMP backend zoo).  Public API surface mirrors
the reference ``__all__`` (stoke/__init__.py:17-43) adapted to TPU concepts.
"""

from stoke_tpu.configs import (
    ActivationCheckpointingConfig,
    AttributionConfig,
    CheckpointConfig,
    CheckpointFormat,
    ClipGradConfig,
    ClipGradNormConfig,
    CommConfig,
    CompileConfig,
    DataParallelConfig,
    DeviceOptions,
    DistributedInitConfig,
    DistributedOptions,
    FleetConfig,
    FSDPConfig,
    HealthConfig,
    LossReduction,
    MemoryConfig,
    MeshConfig,
    NumericsConfig,
    OffloadDiskConfig,
    OffloadOptimizerConfig,
    OffloadParamsConfig,
    OpsPlaneConfig,
    OSSConfig,
    ParamNormalize,
    PartitionRulesConfig,
    PrecisionConfig,
    PrecisionOptions,
    ProfilerConfig,
    ResilienceConfig,
    SDDPConfig,
    ServeConfig,
    TelemetryConfig,
    TensorboardConfig,
    TraceConfig,
    ShardingOptions,
    StokeOptimizer,
)
from stoke_tpu.serving.sampling import SamplingParams
from stoke_tpu.serving.slo import RequestSLO
from stoke_tpu.data import (
    ArrayDataset,
    BucketedDistributedSampler,
    RaggedSequenceDataset,
    StokeDataLoader,
)
from stoke_tpu.engine import (
    DeferredOutput,
    FlaxModelAdapter,
    FunctionalModelAdapter,
    ModelAdapter,
)
from stoke_tpu.facade import Stoke
from stoke_tpu.resilience import PreemptedError
from stoke_tpu.status import StokeStatus, StokeValidationError
from stoke_tpu.telemetry.health import HealthHaltError
from stoke_tpu.utils import force_cpu, init_module

__version__ = "0.1.0"

__all__ = [
    "Stoke",
    "StokeStatus",
    "StokeValidationError",
    "HealthHaltError",
    "PreemptedError",
    "force_cpu",
    "init_module",
    "StokeOptimizer",
    "StokeDataLoader",
    "BucketedDistributedSampler",
    "ArrayDataset",
    "RaggedSequenceDataset",
    # enums
    "DeviceOptions",
    "DistributedOptions",
    "PrecisionOptions",
    "ShardingOptions",
    "ParamNormalize",
    "LossReduction",
    "CheckpointFormat",
    # configs
    "AttributionConfig",
    "PrecisionConfig",
    "ClipGradConfig",
    "ClipGradNormConfig",
    "CommConfig",
    "CompileConfig",
    "DataParallelConfig",
    "MeshConfig",
    "DistributedInitConfig",
    "OSSConfig",
    "SDDPConfig",
    "FleetConfig",
    "FSDPConfig",
    "HealthConfig",
    "MemoryConfig",
    "NumericsConfig",
    "OffloadDiskConfig",
    "OffloadOptimizerConfig",
    "OffloadParamsConfig",
    "OpsPlaneConfig",
    "PartitionRulesConfig",
    "ActivationCheckpointingConfig",
    "CheckpointConfig",
    "ProfilerConfig",
    "ResilienceConfig",
    "ServeConfig",
    "SamplingParams",
    "RequestSLO",
    "TelemetryConfig",
    "TensorboardConfig",
    "TraceConfig",
    # adapters
    "ModelAdapter",
    "FlaxModelAdapter",
    "FunctionalModelAdapter",
    "DeferredOutput",
]

"""Device mesh construction + multi-host rendezvous.

TPU-native replacement for the reference's process-group bootstrap
(stoke/distributed.py:491-538 ``init_process_group`` + MPI discovery;
:759-773 DeepSpeed init; :1308-1316 Horovod init).  One code path:
``jax.distributed.initialize`` for multi-host rendezvous, then a
``jax.sharding.Mesh`` over the global device list.  Collectives become XLA
ops compiled over ICI (intra-slice) / DCN (inter-slice) — there is no NCCL,
no MPI, and no per-backend rendezvous enum (SURVEY.md §2.9).
"""

from __future__ import annotations

import math
import warnings
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from stoke_tpu.configs import (
    DeviceOptions,
    DistributedInitConfig,
    MeshConfig,
)

_DIST_INITIALIZED = False


def _multihost_env_present() -> bool:
    """Detect a multi-host launch environment WITHOUT initializing the JAX
    backend (querying ``jax.process_count()`` here would lock in a
    single-process backend and make a later ``initialize`` ineffective).

    Covers the auto-detection sources ``jax.distributed.initialize`` itself
    uses: explicit JAX coordinator env vars, SLURM/OpenMPI launchers, and
    Cloud TPU pod metadata (the TPU-native replacement for the reference's
    RANK/WORLD_SIZE launcher env + MPI discovery, distributed.py:491-525).
    """
    import os

    if os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    ):
        return True
    for var in ("SLURM_NTASKS", "SLURM_JOB_NUM_NODES", "OMPI_COMM_WORLD_SIZE"):
        try:
            if int(os.environ.get(var, "1")) > 1:
                return True
        except ValueError:
            pass
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if hosts and "," in hosts:  # Cloud TPU pod slice: >1 worker
        return True
    try:
        if int(os.environ.get("MEGASCALE_NUM_SLICES", "1")) > 1:
            return True
    except ValueError:
        pass
    return False


def initialize_distributed(cfg: DistributedInitConfig) -> bool:
    """Idempotent multi-host rendezvous via ``jax.distributed.initialize``.

    Replaces the launcher-env (RANK/WORLD_SIZE/MASTER_ADDR) and mpi4py
    discovery paths of the reference (distributed.py:491-525):

    - explicit fields set → explicit rendezvous (bring-your-own-cluster);
    - all fields ``None`` (the common TPU path) → when a multi-host launch
      environment is detected, ``jax.distributed.initialize()`` with no
      arguments lets JAX auto-infer from TPU pod metadata / SLURM / env vars;
    - single-host (no multi-host env detected) → no-op, returns False.

    Returns True if a multi-process rendezvous was (already) performed.
    """
    global _DIST_INITIALIZED
    if _DIST_INITIALIZED:
        return True
    # the launcher may have called jax.distributed.initialize itself (e.g.
    # a multi-process test harness must rendezvous before ANY backend use);
    # record and respect it rather than re-initializing
    try:
        if jax.distributed.is_initialized():
            _DIST_INITIALIZED = True
            return True
    except AttributeError:
        # older jax exposes no is_initialized(); probe the client state
        # directly (a second initialize() on these versions raises a
        # "must be called before any JAX computations" RuntimeError that
        # the already-initialized fallback below cannot recognize)
        try:
            from jax._src import distributed as _dist

            if getattr(_dist.global_state, "client", None) is not None:
                _DIST_INITIALIZED = True
                return True
        except Exception:
            pass
    explicit = cfg.num_processes is not None or cfg.coordinator_address is not None
    if not explicit and not _multihost_env_present():
        return False
    try:
        if explicit:
            jax.distributed.initialize(
                coordinator_address=cfg.coordinator_address,
                num_processes=cfg.num_processes,
                process_id=cfg.process_id,
                local_device_ids=cfg.local_device_ids,
                initialization_timeout=cfg.initialization_timeout,
            )
        else:
            jax.distributed.initialize(
                initialization_timeout=cfg.initialization_timeout
            )
        _DIST_INITIALIZED = True
        return True
    except RuntimeError as e:
        if "already initialized" in str(e).lower():
            _DIST_INITIALIZED = True
            return True
        raise


def _backend_devices(device: DeviceOptions):
    """Global devices for the selected backend.  ``tpu`` falls back to
    whatever accelerator platform JAX exposes (e.g. the single-chip tunnel
    used in CI) and then to CPU with a warning, so the same script runs
    anywhere (the reference's gpu flag similarly hard-fails only at CUDA
    probe time, status.py:171-188)."""
    if device is DeviceOptions.cpu:
        return jax.devices("cpu")
    try:
        return jax.devices()  # default backend = the accelerator when present
    except RuntimeError:
        warnings.warn("Stoke -- no accelerator platform found; using CPU devices")
        return jax.devices("cpu")


def local_device_count(device: DeviceOptions) -> int:
    if device is DeviceOptions.cpu:
        return len([d for d in jax.local_devices(backend="cpu")])
    return jax.local_device_count()


def build_mesh(
    mesh_config: MeshConfig,
    device: DeviceOptions,
    distributed: bool,
) -> Optional[Mesh]:
    """Build the logical device mesh.

    - not distributed → ``None`` (plain single-device jit; the reference's
      DistributedNull* runners, distributed.py:298-401).
    - distributed → mesh over ALL global devices.  Default 1-D ``("data",)``;
      ``MeshConfig.shape`` reshapes for future model/seq/expert axes.  Axis
      order follows ``jax.sharding.Mesh`` convention: the LAST axis is
      innermost (fastest-varying over ICI neighbors), so put the
      highest-bandwidth-demand axis last when using >1 axis.
    """
    if not distributed:
        return None
    devices = mesh_config.devices
    if devices is None:
        devices = _backend_devices(device)
    devices = np.asarray(devices)
    axes = tuple(mesh_config.axes)
    shape = mesh_config.shape
    if shape is None:
        shape = (devices.size,) + (1,) * (len(axes) - 1)
    shape = tuple(shape)
    if -1 in shape:
        known = math.prod(s for s in shape if s != -1)
        if devices.size % known != 0:
            raise ValueError(
                f"Stoke -- cannot infer mesh shape {shape} from {devices.size} devices"
            )
        shape = tuple(devices.size // known if s == -1 else s for s in shape)
    if math.prod(shape) != devices.size:
        raise ValueError(
            f"Stoke -- mesh shape {shape} does not match {devices.size} devices"
        )
    return Mesh(devices.reshape(shape), axes)

"""Pluggable gradient transport: quantized collectives with error feedback.

ISSUE 2 tentpole.  The DP/ZeRO path syncs full-precision gradients through
compiler-inserted all-reduces (parallel/sharding.py module docstring), so
gradient bytes-on-wire are the scaling tax of every multi-chip config.
EQuARX (arXiv:2506.17615) shows a quantized all-reduce inside XLA recovers
most of that bandwidth at negligible quality cost; this module is the
JAX-level analogue, applied ONCE per optimizer step at the apply boundary:

1. **Bucketed flattening** — gradient leaves are concatenated (tree order)
   into flat fp32 buckets of ``CommConfig.bucket_mb``, so dozens of small
   conv/BN gradients ride ONE collective instead of one each.
2. **Quantized exchange** (``strategy="rs_ag"``) — each bucket goes through
   reduce-scatter → per-chunk-scaled (stochastic-rounding) int8/bf16
   quantize of the owned shard → all-gather of payload + scales →
   dequantize.  ``"all_reduce"`` is the single-stage variant (one quantize,
   one summed exchange).
3. **Error feedback** — the per-leaf residual ``x - transport(x)`` is
   carried in engine state and added back to the NEXT step's gradients
   before quantizing, so quantization error accumulates into the model
   instead of being lost (EF-SGD lineage, arXiv:1901.09847) and int8
   training tracks the fp32 loss trajectory.

Simulation fidelity: under GSPMD the pre-reduction partial gradients are
not addressable from JAX, so the reduce-scatter leg quantizes the
logically-reduced value (one quantization error) where a compiler-level
implementation quantizes each partial (~N errors averaged); wire format,
byte accounting, and the error-feedback machinery are identical, and the
residual absorbs either noise source.  ``dtype="fp32"`` is an exact
pass-through — bit-identical to running without a transport.

The math helpers (:func:`quantize_chunks` / :func:`dequantize_chunks` /
:func:`bucket_layout`) are pure and unit-tested in isolation
(tests/test_collectives.py); :class:`GradTransport` wires them to the mesh.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from stoke_tpu.configs import CommConfig
from stoke_tpu.ops.attention import shard_map
from stoke_tpu.utils.trees import tree_zeros_like

#: int8 wire range is symmetric [-127, 127] (−128 unused so the scale maps
#: max|x| exactly onto the grid and negation is lossless)
_INT8_MAX = 127.0


# --------------------------------------------------------------------------- #
# Pure quantization math
# --------------------------------------------------------------------------- #


def quantize_chunks(
    x: jax.Array,
    chunk: int,
    rng: Optional[jax.Array] = None,
    stochastic: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Per-chunk absmax int8 quantization of a flat fp32 vector.

    ``x`` has length divisible by ``chunk``; elements ``[i*chunk,
    (i+1)*chunk)`` share one f32 scale ``max|x_chunk| / 127``.  Stochastic
    rounding (``floor(v + u)``, ``u ~ U[0,1)``) is unbiased:
    ``E[dequantize(quantize(x))] = x`` — the property that lets error
    feedback converge.  Returns ``(q int8 [L], scales f32 [L/chunk])``.
    """
    x2 = x.reshape(-1, chunk)
    absmax = jnp.max(jnp.abs(x2), axis=1)
    scales = absmax / _INT8_MAX
    safe = jnp.where(scales > 0, scales, 1.0)
    v = x2 / safe[:, None]
    if stochastic:
        if rng is None:
            raise ValueError("stochastic rounding needs an rng key")
        u = jax.random.uniform(rng, v.shape, dtype=v.dtype)
        q = jnp.floor(v + u)
    else:
        q = jnp.round(v)
    q = jnp.clip(q, -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return q.reshape(-1), scales


def dequantize_chunks(q: jax.Array, scales: jax.Array, chunk: int) -> jax.Array:
    """Inverse of :func:`quantize_chunks` (up to rounding): int8 payload +
    per-chunk scales → flat fp32."""
    q2 = q.reshape(-1, chunk).astype(jnp.float32)
    return (q2 * scales[:, None]).reshape(-1)


# --------------------------------------------------------------------------- #
# Bucket layout (host-side, static per leaf-shape signature)
# --------------------------------------------------------------------------- #


class BucketLayout:
    """Static flattening plan: which leaves ride which bucket.

    ``buckets`` is a list of (leaf-index list, payload_elems, padded_elems);
    padding rounds each bucket up to a multiple of ``align`` (world_size ×
    chunk_elems) so reduce-scatter shards and quantization chunks tile
    exactly.  Computed once per gradient-tree shape signature and cached by
    the transport (pure host arithmetic — never traced).
    """

    def __init__(self, sizes: List[int], bucket_elems: int, align: int):
        self.sizes = list(sizes)
        self.buckets: List[Tuple[List[int], int, int]] = []
        current: List[int] = []
        current_elems = 0
        for i, n in enumerate(sizes):
            if current and current_elems + n > bucket_elems:
                self._close(current, current_elems, align)
                current, current_elems = [], 0
            current.append(i)
            current_elems += n
        if current:
            self._close(current, current_elems, align)

    def _close(self, indices: List[int], elems: int, align: int) -> None:
        padded = -(-elems // align) * align
        self.buckets.append((indices, elems, padded))

    @property
    def total_padded_elems(self) -> int:
        return sum(p for _, _, p in self.buckets)


# --------------------------------------------------------------------------- #
# The transport
# --------------------------------------------------------------------------- #


class GradTransport:
    """Applies the configured gradient exchange to a (replicated) gradient
    pytree inside the compiled apply step.

    Stateless apart from host-side layout caches; the carried state
    (residual + rng) lives in the facade and threads through the engine's
    compiled functions like the scaler state does.
    """

    def __init__(
        self,
        cfg: Optional[CommConfig],
        mesh: Optional[Any],
        axis_name: str = "data",
    ):
        self.cfg = cfg
        self.mesh = mesh
        self.axis_name = axis_name
        if mesh is not None and axis_name in mesh.axis_names:
            self.world = int(mesh.shape[axis_name])
        else:
            self.world = 1
        self._layout_cache: Dict[Tuple[int, ...], BucketLayout] = {}

    # ------------------------------ state ------------------------------ #

    @property
    def active(self) -> bool:
        """True when the transport transforms gradients at all
        (``dtype="fp32"`` is a structural pass-through: no state, no
        collectives, bit-identical numerics)."""
        return self.cfg is not None and self.cfg.dtype != "fp32"

    @property
    def error_feedback(self) -> bool:
        return self.active and bool(self.cfg.error_feedback)

    def init_state(self, params: Any, seed: int = 0) -> Dict[str, Any]:
        """Carried transport state: the stochastic-rounding rng stream and
        (with error feedback) the per-leaf residual pytree.  Empty dict when
        inactive, so inactive runs compile the exact same program as before
        the transport existed."""
        if not self.active:
            return {}
        # raw threefry key as host numpy (same layout as
        # jax.random.PRNGKey) — creation must not touch the default
        # accelerator backend; the facade places it explicitly
        state: Dict[str, Any] = {
            "rng": np.array([0, seed], dtype=np.uint32)
        }
        if self.error_feedback:
            state["residual"] = tree_zeros_like(params)
        return state

    def state_shardings(self, grad_shardings: Any, replicated: Any) -> Any:
        """out_shardings tree matching :meth:`init_state`'s structure."""
        if not self.active:
            return {}
        sh: Dict[str, Any] = {"rng": replicated}
        if self.error_feedback:
            sh["residual"] = grad_shardings
        return sh

    # --------------------------- accounting ---------------------------- #

    def bytes_per_step(self, params: Any) -> Optional[Dict[str, int]]:
        """Analytic per-device bytes-on-wire of ONE optimizer step's
        gradient exchange (telemetry; host arithmetic from the static
        layout).  ``prequant`` is what the same schedule moves in fp32;
        ``onwire`` what the configured wire dtype moves.  Ring collectives
        move ``(N-1)/N x payload`` per device per stage; rs_ag and
        all-reduce both comprise two such stages."""
        if self.cfg is None:
            return None
        leaves = jax.tree_util.tree_leaves(params)
        layout = self._layout(self._leaf_sizes(leaves))
        pre, wire = self._wire_bytes(layout.total_padded_elems, stages=2.0)
        return {"prequant": pre, "onwire": wire}

    def bucket_leaf_elems(
        self, params: Any
    ) -> List[List[Tuple[int, int]]]:
        """Per-bucket ``[(leaf_index, n_elems), ...]`` membership of the
        static flattening plan (buckets hold whole leaves, tree order).
        The per-layer numerics observatory (ISSUE 12) maps the sharded
        transport's per-BUCKET error-feedback residual norms back to
        module groups through exactly this table."""
        if self.cfg is None:
            return []
        leaves = jax.tree_util.tree_leaves(params)
        sizes = self._leaf_sizes(leaves)
        layout = self._layout(sizes)
        return [
            [(i, sizes[i]) for i in indices]
            for indices, _, _ in layout.buckets
        ]

    #: residual layout kind this transport carries ("replicated": per-leaf
    #: pytree; zero.py's sharded variant overrides with "sharded": per-
    #: bucket flat buffers) — part of the ISSUE 14 topology descriptor
    layout_kind = "replicated"

    def layout_descriptor(self, params: Any) -> Optional[Dict[str, Any]]:
        """The transport's state-layout descriptor (ISSUE 14): everything
        elastic resume needs to re-map an error-feedback residual saved
        under THIS layout onto a different one — the residual kind, the
        data-axis world size the bucket padding was aligned for, the
        per-leaf element counts (flatten order), and the per-bucket
        (payload, padded) element counts.  None for an inactive
        transport (no state to re-map)."""
        if not self.active:
            return None
        leaves = jax.tree_util.tree_leaves(params)
        sizes = self._leaf_sizes(leaves)
        layout = self._layout(sizes)
        return {
            "kind": self.layout_kind,
            "world": int(self.world),
            "error_feedback": bool(self.error_feedback),
            "leaf_sizes": [int(s) for s in sizes],
            "buckets": [
                [int(elems), int(padded)]
                for _indices, elems, padded in layout.buckets
            ],
        }

    def _wire_bytes(self, elems: int, stages: float) -> Tuple[int, int]:
        """Per-device bytes of ``stages`` ring stages over one padded
        payload — ``(N-1)/N × payload`` each — in fp32 (``pre``) vs the
        configured wire dtype (``wire``; int8 = payload + one f32 scale
        per chunk).  The one copy of the wire-format formula both the
        replicated (2 stages) and the sharded (zero.py, 1 stage)
        accountings cite."""
        chunks = elems // max(self.cfg.chunk_elems, 1)
        ring = stages * (self.world - 1) / max(self.world, 1)
        pre = ring * 4.0 * elems
        if self.cfg.dtype == "fp32":
            wire = pre
        elif self.cfg.dtype == "bf16":
            wire = ring * 2.0 * elems
        else:  # int8 payload + one f32 scale per chunk
            wire = ring * (1.0 * elems + 4.0 * chunks)
        return int(pre), int(wire)

    # ----------------------------- apply ------------------------------- #

    def apply(
        self, grads: Any, state: Dict[str, Any]
    ) -> Tuple[Any, Dict[str, Any]]:
        """Transport a gradient pytree; returns ``(synced_grads,
        new_state)``.  Error feedback is the outer formulation: the residual
        is whatever the transport lost this step (``x - transport(x)``),
        re-injected next step — exact for any inner exchange, and exactly
        zero for the fp32 pass-through."""
        if not self.active:
            return grads, state
        rng = state["rng"]
        new_rng, sub = jax.random.split(rng)
        if self.error_feedback:
            x = jax.tree_util.tree_map(
                lambda g, r: g + r.astype(g.dtype), grads, state["residual"]
            )
        else:
            x = grads
        y = self._exchange_tree(x, sub)
        new_state: Dict[str, Any] = {"rng": new_rng}
        if self.error_feedback:
            new_state["residual"] = jax.tree_util.tree_map(
                lambda a, b: (a - b).astype(a.dtype), x, y
            )
        return y, new_state

    # ----------------------- bucketed tree plumbing -------------------- #

    def _layout(self, sizes: List[int]) -> BucketLayout:
        key = tuple(sizes)
        if key not in self._layout_cache:
            cfg = self.cfg
            bucket_elems = max(int(cfg.bucket_mb * 2**20 / 4), 1)
            align = max(self.world, 1) * max(cfg.chunk_elems, 1)
            self._layout_cache[key] = BucketLayout(sizes, bucket_elems, align)
        return self._layout_cache[key]

    @staticmethod
    def _leaf_sizes(leaves: List[Any]) -> List[int]:
        return [int(np.prod(l.shape)) if l.shape else 1 for l in leaves]

    def _bucketed_exchange(
        self, tree: Any, rng: jax.Array, exchange: Any
    ) -> Tuple[Any, List[Any]]:
        """Shared flatten/pad/slice-out plumbing over the bucket layout:
        concatenates each bucket's leaves into one padded flat f32 buffer,
        calls ``exchange(bucket_index, flat, per_bucket_key) -> (out_flat,
        extra)``, slices the outputs back to leaf shapes/dtypes, and
        returns ``(tree, [extra per bucket])`` — the one copy of the
        packing both the replicated and the sharded (zero.py) schedules
        ride."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        sizes = self._leaf_sizes(leaves)
        layout = self._layout(sizes)
        outs: List[Any] = [None] * len(leaves)
        extras: List[Any] = []
        for b, (indices, elems, padded) in enumerate(layout.buckets):
            flat = jnp.concatenate(
                [leaves[i].astype(jnp.float32).reshape(-1) for i in indices]
            )
            if padded > elems:
                flat = jnp.pad(flat, (0, padded - elems))
            out, extra = exchange(b, flat, jax.random.fold_in(rng, b))
            extras.append(extra)
            off = 0
            for i in indices:
                n = sizes[i]
                outs[i] = (
                    out[off:off + n]
                    .reshape(leaves[i].shape)
                    .astype(leaves[i].dtype)
                )
                off += n
        return jax.tree_util.tree_unflatten(treedef, outs), extras

    def _exchange_tree(self, tree: Any, rng: jax.Array) -> Any:
        out, _ = self._bucketed_exchange(
            tree, rng,
            lambda b, flat, key: (self._exchange_flat(flat, key), None),
        )
        return out

    # ------------------------- flat exchange --------------------------- #

    def _exchange_flat(self, flat: jax.Array, rng: jax.Array) -> jax.Array:
        """One bucket through the configured exchange.  With a real mesh
        axis the collectives run inside shard_map (explicit
        psum_scatter/all_gather on the wire payload); single-device falls
        back to the same quantization round trip without collectives, so
        the numerics are testable anywhere."""
        if self.mesh is None or self.world <= 1:
            return self._roundtrip_local(flat, rng)
        fn = shard_map(
            lambda x, key: self._wire_exchange(x, key),
            self.mesh,
            in_specs=(P(), P()),
            out_specs=P(),
        )
        return fn(flat, rng)

    def _roundtrip_local(self, flat: jax.Array, rng: jax.Array) -> jax.Array:
        cfg = self.cfg
        k1, k2 = jax.random.split(rng)
        if cfg.strategy == "rs_ag":
            flat = self._quant_roundtrip(flat, k1)
        return self._quant_roundtrip(flat, k2)

    def _quant_roundtrip(self, x: jax.Array, key: jax.Array) -> jax.Array:
        cfg = self.cfg
        if cfg.dtype == "bf16":
            return x.astype(jnp.bfloat16).astype(jnp.float32)
        q, s = quantize_chunks(
            x, cfg.chunk_elems, key, cfg.stochastic_rounding
        )
        return dequantize_chunks(q, s, cfg.chunk_elems)

    def _wire_exchange(self, x: jax.Array, key: jax.Array) -> jax.Array:
        """Per-shard body (inside shard_map): the actual collective
        schedule.  ``x`` arrives replicated (the logically-reduced bucket);
        the quantize→exchange→dequantize path models the wire format of
        the compiler-level quantized collective (module docstring)."""
        cfg = self.cfg
        axis = self.axis_name
        n = self.world
        chunk = cfg.chunk_elems
        # both schedules put the local tensor on the wire first; the
        # round-trip helper IS the wire format (shared with the
        # single-device fallback so the two paths cannot diverge)
        xq = self._quant_roundtrip(x, key)
        if cfg.strategy == "all_reduce":
            # single-stage: exchange the wire-format payload, average.
            # One quantization error total.
            return lax.psum(xq, axis) / n
        # rs_ag: reduce-scatter the wire-format payload, then each device
        # quantizes the shard it owns and all-gathers payload + scales
        # (weight-update-sharding-compatible; both legs ride the wire dtype)
        own = lax.psum_scatter(xq, axis, scatter_dimension=0, tiled=True) / n
        if cfg.dtype == "bf16":
            own_w = own.astype(jnp.bfloat16)
            gathered = lax.all_gather(own_w, axis, axis=0, tiled=True)
            return gathered.astype(jnp.float32)
        key2 = jax.random.fold_in(key, lax.axis_index(axis) + 1)
        q2, s2 = quantize_chunks(own, chunk, key2, cfg.stochastic_rounding)
        qg = lax.all_gather(q2, axis, axis=0, tiled=True)
        sg = lax.all_gather(s2, axis, axis=0, tiled=True)
        return dequantize_chunks(qg, sg, chunk)

"""ZeRO-parity quantized collectives: weight-update sharding × int8 wire.

ISSUE 8 tentpole.  PR 2's replicated transport (parallel/collectives.py)
owns the whole gradient collective — reduce-scatter, quantize, all-gather —
and therefore *needed* the replicated grad buffer of tiers none/oss; the
status rules banned it under sddp/fsdp, so the configs that most need wire
reduction (large-model sharded-optimizer runs) paid full fp32 gradient
bytes.  This module lifts the ban by composing the quantized wire format
with cross-replica weight-update sharding (arXiv:2004.13336 — the
ZeRO-style partition stoke exposed as OSS/SDDP) in the EQuARX style
(arXiv:2506.17615):

1. **Quantized reduce-scatter** — each bucket's gradient leg is ONE ring
   stage: the int8(+scales)/bf16 payload reduce-scatters and every replica
   keeps only its 1/N shard.  There is no gradient all-gather — half the
   collective traffic of the replicated rs_ag schedule before quantization
   even starts.
2. **Per-shard error feedback** — the residual is carried *sharded*: each
   replica stores only its partition's residual (1/N memory), injects it
   into its owned shard before quantization, and carries the new loss
   ``(shard + residual) - wire(shard + residual)``.  Per shard this is
   exactly the PR 2 EF recurrence, so the convergence argument
   (arXiv:1901.09847 lineage) transfers unchanged.
3. **Shard-local optimizer step + param all-gather** — the transported
   gradients leave this module placement-sharded over the data axis; the
   tier's optimizer-state partition (oss/sddp/fsdp placement rules) makes
   the optax update shard-local under GSPMD, and the updated parameters
   all-gather back to their tier placement (replicated for none/oss/sddp;
   fsdp params stay sharded — its gathers happen at use, not here).  The
   all-gather is bucket-granular — each bucket's exchange is an
   independent program region, so XLA overlaps a finished bucket's param
   gather with the remaining shard updates.

Simulation fidelity (same caveat as PR 2, module docstring there): under
GSPMD the pre-reduction partial gradients are not addressable from JAX, so
the shard is quantized after the logical reduce (one quantization error
where a compiler-level implementation averages ~N); the wire format, byte
accounting, shard placement, and the per-shard EF recurrence are identical,
and the residual absorbs either noise source.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from stoke_tpu.configs import CommConfig, ShardingOptions, comm_shard_updates
from stoke_tpu.ops.attention import shard_map
from stoke_tpu.parallel.collectives import GradTransport


class ShardedGradTransport(GradTransport):
    """Weight-update-sharded variant of the gradient transport.

    Same engine-facing contract as :class:`GradTransport` (``init_state`` /
    ``state_shardings`` / ``bytes_per_step`` / ``apply``), different
    collective schedule and state layout:

    - ``apply`` returns gradients whose placement is sharded over the data
      axis (the quantized reduce-scatter's output); the caller's optimizer
      update is then shard-local and the param all-gather is the second —
      separately accounted — wire leg.
    - ``state["residual"]`` is a TUPLE of flat per-bucket f32 buffers
      (logical ``[padded_elems]``, placed ``P(axis)``) instead of PR 2's
      replicated per-leaf pytree: each replica materializes 1/N of it.

    ``params_replicated`` says whether the updated parameters all-gather at
    the apply boundary (tiers none/oss/sddp) or stay sharded (fsdp) — it
    only affects the analytic ``param_gather`` byte accounting.

    Per-layer wire-error attribution (ISSUE 12): the per-bucket residual
    buffers are exactly the quantization error this schedule is carrying;
    :meth:`GradTransport.bucket_leaf_elems` exposes the bucket → leaf
    membership so ``telemetry.numerics.wire_residual_group_norms`` can map
    each bucket's norm back to the module groups whose gradients ride it.
    """

    #: ISSUE 14 topology descriptor: the residual is per-bucket flat
    #: buffers placed P(axis), not the replicated per-leaf pytree
    layout_kind = "sharded"

    def __init__(
        self,
        cfg: Optional[CommConfig],
        mesh: Optional[Any],
        axis_name: str = "data",
        params_replicated: bool = True,
    ):
        super().__init__(cfg, mesh, axis_name)
        self.params_replicated = bool(params_replicated)

    # ------------------------------ state ------------------------------ #

    def _bucket_layout_for(self, params: Any):
        leaves = jax.tree_util.tree_leaves(params)
        return self._layout(self._leaf_sizes(leaves))

    def init_state(self, params: Any, seed: int = 0) -> Dict[str, Any]:
        """Carried state: rng stream + (with EF) one flat residual buffer
        per bucket.  Host numpy — the facade/engine places it onto the
        sharded layout via :meth:`state_shardings`."""
        if not self.active:
            return {}
        state: Dict[str, Any] = {"rng": np.array([0, seed], dtype=np.uint32)}
        if self.error_feedback:
            layout = self._bucket_layout_for(params)
            self._n_buckets = len(layout.buckets)
            state["residual"] = tuple(
                np.zeros((padded,), np.float32)
                for _, _, padded in layout.buckets
            )
        return state

    def state_shardings(self, grad_shardings: Any, replicated: Any) -> Any:
        """The residual buffers shard over the data axis — the 1/N-memory
        claim is this placement (``grad_shardings`` is ignored: the
        residual's layout is the bucket layout, not the leaf layout)."""
        if not self.active:
            return {}
        sh: Dict[str, Any] = {"rng": replicated}
        if self.error_feedback:
            if self.mesh is not None:
                shard = NamedSharding(self.mesh, P(self.axis_name))
            else:
                shard = replicated
            n = getattr(self, "_n_buckets", None)
            # one sharding per residual buffer; the count is fixed by
            # init_state, which resolved the bucket layout
            if n is None:
                raise RuntimeError(
                    "state_shardings called before init_state resolved the "
                    "bucket layout"
                )
            sh["residual"] = tuple(shard for _ in range(n))
        return sh

    # --------------------------- accounting ---------------------------- #

    def bytes_per_step(self, params: Any) -> Optional[Dict[str, int]]:
        """Analytic per-device bytes-on-wire of one sharded optimizer step.

        The gradient leg is ONE ring reduce-scatter stage —
        ``(N-1)/N × payload`` per device — in the wire dtype (``onwire``)
        vs fp32 (``prequant``).  ``param_gather`` is the second leg: the
        updated-parameter all-gather back to the replicated tier placement
        (fp32 — parameters are master weights), 0 under fsdp where params
        stay sharded and the use-time gathers are the forward's, unchanged
        by the transport."""
        if self.cfg is None:
            return None
        leaves = jax.tree_util.tree_leaves(params)
        sizes = self._leaf_sizes(leaves)
        layout = self._layout(sizes)
        pre, wire = self._wire_bytes(layout.total_padded_elems, stages=1.0)
        ring = (self.world - 1) / max(self.world, 1)
        gather = ring * 4.0 * sum(sizes) if self.params_replicated else 0.0
        return {
            "prequant": pre,
            "onwire": wire,
            "param_gather": int(gather),
        }

    # ----------------------------- apply ------------------------------- #

    def apply(
        self, grads: Any, state: Dict[str, Any]
    ) -> Tuple[Any, Dict[str, Any]]:
        """Sharded transport of a gradient pytree: per bucket, quantized
        reduce-scatter with per-shard error feedback.  Returns gradients
        placed sharded over the data axis (``new_state["residual"]``
        likewise) — the caller's optimizer update consumes the shards."""
        if not self.active:
            return grads, state
        new_rng, sub = jax.random.split(state["rng"])
        residuals = state.get("residual")

        def exchange(b, flat, key):
            res_b = residuals[b] if residuals is not None else None
            return self._exchange_sharded(flat, res_b, key)

        out, new_res = self._bucketed_exchange(grads, sub, exchange)
        new_state: Dict[str, Any] = {"rng": new_rng}
        if residuals is not None:
            new_state["residual"] = tuple(new_res)
        return out, new_state

    # ------------------------- flat exchange --------------------------- #

    def _exchange_sharded(
        self,
        flat: jax.Array,
        res: Optional[jax.Array],
        rng: jax.Array,
    ) -> Tuple[jax.Array, Optional[jax.Array]]:
        """One bucket through the sharded schedule.  With a real mesh axis
        the reduce-scatter + per-shard quantize run inside shard_map (the
        output stays partitioned, out_specs ``P(axis)``); single-device
        falls back to the same quantization round trip without collectives
        so the numerics are testable anywhere."""
        if self.mesh is None or self.world <= 1:
            x = flat if res is None else flat + res
            y = self._quant_roundtrip(x, rng)
            return y, (None if res is None else x - y)
        axis = self.axis_name
        n = self.world

        def _body(x, res_shard, key):
            # x: the full (logically-reduced) bucket; res_shard: this
            # replica's residual partition.  One ring stage: the shard
            # owner ends with the wire-format value of its partition.
            own = lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True) / n
            if res_shard is not None:
                own = own + res_shard
            key_i = jax.random.fold_in(key, lax.axis_index(axis) + 1)
            wire = self._quant_roundtrip(own, key_i)
            if res_shard is None:
                return (wire,)
            return wire, own - wire

        if res is None:
            fn = shard_map(
                lambda x, key: _body(x, None, key),
                self.mesh,
                in_specs=(P(), P()),
                out_specs=(P(axis),),
            )
            (out,) = fn(flat, rng)
            return out, None
        fn = shard_map(
            _body,
            self.mesh,
            in_specs=(P(), P(axis), P()),
            out_specs=(P(axis), P(axis)),
        )
        return fn(flat, res, rng)


# --------------------------------------------------------------------------- #
# Residual partition algebra (ISSUE 14 tentpole b: topology-elastic resume)
# --------------------------------------------------------------------------- #
#
# The error-feedback residual is logically ONE flat f32 vector over the
# parameter elements (flatten order) — every layout is just a packing of
# it: the replicated transport packs it per leaf, the sharded transport as
# per-bucket padded buffers whose bucket splits and padding depend on
# ``bucket_mb``, ``chunk_elems``, and the data-axis WORLD SIZE (the ZeRO
# weight-update-sharding partition rule, arXiv:2004.13336).  Re-mapping a
# residual saved on one topology onto another is therefore: unpack to the
# flat vector under the SAVED descriptor, repack under the CURRENT one.
# Pure host numpy, unit-testable without a mesh.


def residual_to_flat(residual: Any, desc: Dict[str, Any]) -> np.ndarray:
    """Unpack a host-side residual into the flat per-element f32 vector
    under its layout descriptor (``GradTransport.layout_descriptor``)."""
    if desc["kind"] == "sharded":
        parts = [
            np.asarray(buf, np.float32).reshape(-1)[:elems]
            for buf, (elems, _padded) in zip(residual, desc["buckets"])
        ]
        return (
            np.concatenate(parts) if parts else np.zeros((0,), np.float32)
        )
    leaves = jax.tree_util.tree_leaves(residual)
    if not leaves:
        return np.zeros((0,), np.float32)
    return np.concatenate(
        [np.asarray(l, np.float32).reshape(-1) for l in leaves]
    )


def flat_to_residual(
    flat: np.ndarray, desc: Dict[str, Any], template: Any
) -> Any:
    """Repack the flat residual vector under a target layout descriptor.
    ``template`` is the CURRENT run's residual state (host or device) —
    the treedef/leaf-shape source for the replicated packing; only its
    structure is read."""
    flat = np.asarray(flat, np.float32).reshape(-1)
    total = int(sum(desc["leaf_sizes"]))
    if flat.size != total:
        raise ValueError(
            f"Stoke -- residual re-map size mismatch: flat vector has "
            f"{flat.size} elements, target layout covers {total} "
            f"(different model?)"
        )
    if desc["kind"] == "sharded":
        out, off = [], 0
        for elems, padded in desc["buckets"]:
            buf = np.zeros((int(padded),), np.float32)
            buf[:elems] = flat[off:off + elems]
            off += elems
            out.append(buf)
        return tuple(out)
    leaves, treedef = jax.tree_util.tree_flatten(template)
    out, off = [], 0
    for leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        n = int(np.prod(shape)) if shape else 1
        out.append(flat[off:off + n].reshape(shape))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def remap_residual(
    residual: Any,
    saved_desc: Dict[str, Any],
    target_desc: Dict[str, Any],
    target_template: Any,
) -> Any:
    """Re-map a host-side residual saved under ``saved_desc`` onto
    ``target_desc``'s layout (different world size, bucket padding, or
    replicated↔sharded kind).  Raises ``ValueError`` on element-count
    mismatch — a residual from a different MODEL cannot re-map and the
    caller degrades to dropping it."""
    flat = residual_to_flat(residual, saved_desc)
    total = int(sum(target_desc["leaf_sizes"]))
    if flat.size != total:
        raise ValueError(
            f"Stoke -- residual re-map: saved residual covers {flat.size} "
            f"elements, current model {total} (incompatible checkpoint)"
        )
    return flat_to_residual(flat, target_desc, target_template)


def make_transport(
    cfg: Optional[CommConfig], rules: Optional[Any]
) -> GradTransport:
    """Transport factory: the single place the engine decides between the
    PR 2 replicated exchange and the ISSUE 8 sharded weight-update path.
    The resolution (:func:`~stoke_tpu.configs.comm_shard_updates`) is shared
    with the status legality rules, so an engine can never construct a
    combination status would reject."""
    mesh = rules.mesh if rules is not None else None
    axis = rules.axis_name if rules is not None else "data"
    tier = rules.tier if rules is not None else ShardingOptions.none
    if rules is not None and comm_shard_updates(cfg, tier):
        return ShardedGradTransport(
            cfg, mesh, axis,
            params_replicated=tier is not ShardingOptions.fsdp,
        )
    return GradTransport(cfg, mesh, axis)

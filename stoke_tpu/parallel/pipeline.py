"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

Capability upside beyond the reference (SURVEY.md §2.8: "no pipeline
parallelism").  The pattern: identical stages live on consecutive devices of
a ``stage`` mesh axis (stage s holds slice s of the stacked stage
parameters); microbatches stream through — each tick every stage processes
the activation it holds and ``ppermute``s the result to its neighbor (ICI
link), so after a fill phase of S-1 ticks all stages compute concurrently.

Differentiation is automatic: the transpose of ``ppermute`` is the reverse
rotation, so ``jax.grad`` of the pipelined function IS backward pipelining
(outputs of fill/drain garbage ticks are masked out, so their gradient
contribution is exactly zero).

This is the composable building block (function-level, mesh in hand); full
facade integration (stage-stacked optimizers etc.) composes via
``PartitionRulesConfig`` placing the stacked stage dimension.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from stoke_tpu.ops.attention import shard_map


def pipeline(
    stage_fn: Callable,
    mesh: Mesh,
    axis_name: str = "stage",
) -> Callable:
    """Build a pipelined apply from a single-stage function.

    Args:
        stage_fn: ``stage_fn(stage_params, x) -> y`` with ``y`` shaped like
            ``x`` (stages must be shape-preserving, e.g. transformer blocks).
        mesh: mesh containing ``axis_name`` (size S = number of stages).
        axis_name: the pipeline axis.

    Returns ``pipelined(stacked_params, xs)`` where ``stacked_params`` leaves
    carry a leading stage dimension [S, ...] and ``xs`` is the microbatch
    stream [M, micro_batch, ...]; result is [M, micro_batch, ...] equal to
    running all S stages sequentially over each microbatch.
    """
    S = mesh.shape[axis_name]

    def per_shard(params_local, xs):
        # params_local leaves: [1, ...] (this stage's slice) -> squeeze
        params = jax.tree_util.tree_map(lambda a: a[0], params_local)
        stage = lax.axis_index(axis_name)
        M = xs.shape[0]
        T = M + S - 1  # fill + steady + drain ticks
        fwd = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            act, outbuf = carry
            # stage 0 ingests microbatch t (clamped during drain)
            micro = lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
            )
            inp = jnp.where(stage == 0, micro, act)
            out = stage_fn(params, inp)
            # the LAST stage emits microbatch t-(S-1) once the pipe is full
            widx = t - (S - 1)
            updated = lax.dynamic_update_slice_in_dim(
                outbuf, out[None].astype(outbuf.dtype),
                jnp.clip(widx, 0, M - 1), axis=0,
            )
            valid = jnp.logical_and(stage == S - 1, widx >= 0)
            outbuf = jnp.where(valid, updated, outbuf)
            act = lax.ppermute(out, axis_name, fwd)
            return (act, outbuf), None

        act0 = jnp.zeros_like(xs[0])
        outbuf0 = jnp.zeros_like(xs)
        (act, outbuf), _ = lax.scan(tick, (act0, outbuf0), jnp.arange(T))
        # only the last stage holds real outputs; psum replicates them
        outbuf = jnp.where(stage == S - 1, outbuf, 0.0)
        return lax.psum(outbuf, axis_name)

    def pipelined(stacked_params, xs):
        param_specs = jax.tree_util.tree_map(
            lambda a: P(axis_name, *([None] * (a.ndim - 1))), stacked_params
        )
        fn = shard_map(
            per_shard, mesh,
            in_specs=(param_specs, P()),
            out_specs=P(),
        )
        return fn(stacked_params, xs)

    return pipelined


def stack_stage_params(param_trees) -> object:
    """Stack S per-stage parameter pytrees into one tree with a leading
    stage dimension (the layout :func:`pipeline` expects)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *param_trees
    )

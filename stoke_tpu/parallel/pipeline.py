"""Pipeline parallelism: microbatch pipelining over a mesh axis.

Capability upside beyond the reference (SURVEY.md §2.8: "no pipeline
parallelism").  The pattern: stages live on consecutive devices of a
``stage`` mesh axis (device d holds slice(s) of the stacked stage
parameters); microbatches stream through — each tick every device processes
the activation it holds and ``ppermute``s the result to its neighbor (an ICI
link), so after a fill phase of S-1 ticks all devices compute concurrently.

Two schedules, one implementation (``rounds``):

- ``rounds=1`` — classic GPipe fill-drain: L = S stages, bubble fraction
  (S-1)/(M+S-1) for M microbatches.
- ``rounds=V>1`` — circular/interleaved schedule: L = V·S stages, device d
  holds stages {d, d+S, ..., d+(V-1)S}; each microbatch laps the ring V
  times (a returning activation waits in a device-local slot buffer until
  its round's stream position comes up).  Same total compute, bubble
  fraction (S-1)/(V·M+S-1) — the interleaving the fill-drain schedule
  can't reach (Megatron-LM interleaved / praxis circular equivalent).

Differentiation is automatic: the transpose of ``ppermute`` is the reverse
rotation, so ``jax.grad`` of the pipelined function IS backward pipelining
(fill/drain garbage ticks are masked, so their gradient contribution is
exactly zero).  ``remat=True`` wraps each per-tick stage application in
``jax.checkpoint``: saved residuals shrink to the wire activations — the
activation-memory profile 1F1B exists for, without a hand-written backward
schedule (the backward pass still pipelines tick-by-tick through the
transposed rotation).

The wire (inter-stage activation) may be any pytree, but its
structure/shapes must be uniform across stages — that is fundamental to a
rotating SPMD schedule.  Non-uniform INPUT/OUTPUT edges (embedding in, LM
head out) compose OUTSIDE the rotation via :func:`pipeline_with_edges`.

This is the composable building block (function-level, mesh in hand); full
facade integration (stage-stacked optimizers etc.) composes via
``PartitionRulesConfig`` placing the stacked stage dimension.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from stoke_tpu.ops.attention import shard_map


def _tree_where(pred, a, b):
    return jax.tree_util.tree_map(
        lambda x, y: jnp.where(pred, x, y), a, b
    )


def _tree_index(tree, i):
    return jax.tree_util.tree_map(
        lambda a: lax.dynamic_index_in_dim(a, i, axis=0, keepdims=False), tree
    )


def _tree_update(tree, update, i):
    return jax.tree_util.tree_map(
        lambda buf, u: lax.dynamic_update_slice_in_dim(
            buf, u[None].astype(buf.dtype), i, axis=0
        ),
        tree,
        update,
    )


def pipeline(
    stage_fn: Callable,
    mesh: Mesh,
    axis_name: str = "stage",
    *,
    rounds: int = 1,
    remat: bool = False,
    data_axis: Optional[str] = None,
) -> Callable:
    """Build a pipelined apply from a single-stage function.

    Args:
        stage_fn: ``stage_fn(stage_params, x) -> y`` with ``y`` a pytree of
            the same structure/shapes as ``x`` (the uniform wire; transformer
            blocks are the canonical case — non-uniform edges go through
            :func:`pipeline_with_edges`).
        mesh: mesh containing ``axis_name`` (size S = pipeline devices).
        axis_name: the pipeline axis.
        rounds: V virtual stages per device (circular schedule).  Total
            stages L = V·S; ``stacked_params`` must carry L on the leading
            dim.  V=1 is GPipe fill-drain.
        remat: rematerialize each per-tick stage application
            (``jax.checkpoint``) so backward residuals hold only wire
            activations — the 1F1B activation-memory profile.
        data_axis: optional mesh axis for dp×pp composition: the microbatch
            stream's BATCH dim (axis 1 of ``[M, micro_batch, ...]`` leaves)
            shards over it, so each data-parallel group runs the same
            pipeline schedule on its batch slice (stage ``ppermute``s stay
            within a group; gradient all-reduce over ``data_axis`` is
            GSPMD's job at the consumer).  Without it, extra mesh axes see
            the stream replicated.

    Returns ``pipelined(stacked_params, xs)`` where ``stacked_params``
    leaves carry a leading stage dimension [L, ...] and ``xs`` is the
    microbatch stream (pytree of [M, micro_batch, ...], M ≥ S); result has
    the shape of ``xs`` and equals running all L stages sequentially over
    each microbatch.
    """
    S = mesh.shape[axis_name]
    V = int(rounds)
    if V < 1:
        raise ValueError(f"rounds must be >= 1, got {rounds}")
    run_stage = jax.checkpoint(stage_fn) if remat else stage_fn

    def per_shard(params_local, xs):
        # params_local leaves: [V, 1, ...] (this device's V stage slices,
        # shard_map leaves the sharded stage dim as size 1) -> [V, ...]
        params_local = jax.tree_util.tree_map(lambda a: a[:, 0], params_local)
        stage = lax.axis_index(axis_name)
        leaves = jax.tree_util.tree_leaves(xs)
        M = leaves[0].shape[0]
        if V > 1 and M < S:
            # circular timing: a parked activation must be consumed before
            # its slot is re-parked, which needs M >= S
            raise ValueError(
                f"circular schedule needs at least S={S} microbatches, got {M}"
            )
        T = V * M + S - 1  # fill + circular steady state
        fwd = [(i, (i + 1) % S) for i in range(S)]
        micro_like = _tree_index(xs, 0)

        def tick(carry, t):
            act, queue, outbuf = carry
            # this device's stream position / round / microbatch this tick
            p = t - stage
            r = jnp.clip(p // M, 0, V - 1)
            m = jnp.clip(p - r * M, 0, M - 1)
            # device 0 sources its input: round 0 ingests microbatch m from
            # the stream; later rounds consume the returning activation
            # parked in this microbatch's queue slot
            ingest = _tree_index(xs, m)
            if V > 1:
                parked = _tree_index(queue, m)
                first_in = _tree_where(r == 0, ingest, parked)
            else:
                first_in = ingest
            inp = _tree_where(stage == 0, first_in, act)
            # apply this round's stage slice
            params_r = _tree_index(params_local, r)
            out = run_stage(params_r, inp)
            # the LAST device finishing round V-1 emits microbatch m
            done = jnp.logical_and(stage == S - 1, p >= (V - 1) * M)
            outbuf = _tree_where(done, _tree_update(outbuf, out, m), outbuf)
            act_next = jax.tree_util.tree_map(
                lambda a: lax.ppermute(a, axis_name, fwd), out
            )
            # device 0 parks the activation arriving from device S-1 (it
            # belongs to stream position t-(S-1); consumed at tick p'+M+...,
            # i.e. strictly later since M >= S) — only meaningful for V > 1
            if V > 1:
                p_in = (t + 1) - (S - 1) - 1  # position of act leaving S-1 at t
                m_in = jnp.clip(p_in - jnp.clip(p_in // M, 0, V - 1) * M, 0, M - 1)
                park = jnp.logical_and(stage == 0, p_in >= 0)
                queue = _tree_where(
                    park, _tree_update(queue, act_next, m_in), queue
                )
            return (act_next, queue, outbuf), None

        act0 = jax.tree_util.tree_map(jnp.zeros_like, micro_like)
        # the return-queue (one wire slot per microbatch) only exists for
        # the circular schedule; GPipe carries no extra state
        queue0 = jax.tree_util.tree_map(jnp.zeros_like, xs) if V > 1 else ()
        outbuf0 = jax.tree_util.tree_map(jnp.zeros_like, xs)
        (act, queue, outbuf), _ = lax.scan(
            tick, (act0, queue0, outbuf0), jnp.arange(T)
        )
        # only the last device holds real outputs
        outbuf = _tree_where(stage == S - 1, outbuf, jax.tree_util.tree_map(
            jnp.zeros_like, outbuf
        ))
        if M % S == 0:
            # emit via reduce-scatter: every output byte crosses the wire
            # ONCE (vs twice for a full psum of the mostly-zero buffer) and
            # each device ends up owning M/S microbatch rows — the same
            # global [M, ...] array, sharded over the stage axis, so
            # downstream per-microbatch work (edges, loss) parallelizes
            # over stages instead of replicating, and GSPMD reshards only
            # if something actually needs replication
            return jax.tree_util.tree_map(
                lambda a: lax.psum_scatter(
                    a, axis_name, scatter_dimension=0, tiled=True
                ),
                outbuf,
            )
        # indivisible M: replicate via psum (correct for any M)
        return jax.tree_util.tree_map(
            lambda a: lax.psum(a, axis_name), outbuf
        )

    def pipelined(stacked_params, xs):
        def _reshape(a):
            if a.shape[0] != V * S:
                raise ValueError(
                    f"stacked params lead dim {a.shape[0]} != rounds×stages "
                    f"= {V}×{S}"
                )
            return a.reshape(V, S, *a.shape[1:])

        grouped = jax.tree_util.tree_map(_reshape, stacked_params)
        param_specs = jax.tree_util.tree_map(
            lambda a: P(None, axis_name, *([None] * (a.ndim - 2))), grouped
        )
        d = data_axis  # None -> batch dim replicated over extra axes

        def _xs_spec(a):
            if d is None or a.ndim < 2:
                return P()
            return P(None, d, *([None] * (a.ndim - 2)))

        def _out_spec(a):
            if d is None or a.ndim < 2:
                return P(axis_name, *([None] * (a.ndim - 1)))
            return P(axis_name, d, *([None] * (a.ndim - 2)))

        xs_specs = jax.tree_util.tree_map(_xs_spec, xs)
        M = jax.tree_util.tree_leaves(xs)[0].shape[0]
        # match the emit path: reduce-scattered outputs are sharded over the
        # stage axis on the microbatch dim (same global array)
        out_specs = (
            jax.tree_util.tree_map(_out_spec, xs)
            if M % S == 0
            else xs_specs
        )
        fn = shard_map(
            per_shard, mesh,
            in_specs=(param_specs, xs_specs),
            out_specs=out_specs,
        )
        return fn(grouped, xs)

    return pipelined


def pipeline_with_edges(
    first_fn: Optional[Callable],
    stage_fn: Callable,
    last_fn: Optional[Callable],
    mesh: Mesh,
    axis_name: str = "stage",
    **pipeline_kwargs,
) -> Callable:
    """Pipeline with non-uniform input/output edges.

    The rotating schedule needs a uniform wire, but a real model's edges are
    not uniform (token ids → embeddings in, hidden → vocab logits out).
    The edges run OUTSIDE the rotation, vmapped over the microbatch stream
    (they are data-parallel work, not pipeline work):

        run((first_params, last_params), stacked_params, xs)
          == last_fn(last_params, pipeline(stage_fn)(first_fn(first_params, xs)))

    ``first_fn(first_params, micro) -> wire`` and
    ``last_fn(last_params, wire) -> out`` apply per microbatch; pass None to
    skip an edge.
    """
    piped = pipeline(stage_fn, mesh, axis_name, **pipeline_kwargs)

    def run(edge_params, stacked_params, xs):
        first_params, last_params = edge_params
        wire = (
            jax.vmap(lambda x: first_fn(first_params, x))(xs)
            if first_fn is not None
            else xs
        )
        mid = piped(stacked_params, wire)
        return (
            jax.vmap(lambda a: last_fn(last_params, a))(mid)
            if last_fn is not None
            else mid
        )

    return run


def stack_stage_params(param_trees) -> object:
    """Stack per-stage parameter pytrees into one tree with a leading stage
    dimension (the layout :func:`pipeline` expects; for ``rounds=V`` pass
    all L = V·S stage trees in order)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *param_trees
    )

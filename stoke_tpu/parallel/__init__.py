"""SPMD parallelism layer: mesh construction, multi-host rendezvous, and the
sharding-tier rules that replace the reference's per-backend process wrappers
(DDP/Horovod/DeepSpeed + fairscale OSS/SDDP/FSDP) with one engine
(SURVEY.md §2.9, §7)."""

from stoke_tpu.parallel.mesh import build_mesh, initialize_distributed, local_device_count
from stoke_tpu.parallel.pipeline import pipeline, stack_stage_params
from stoke_tpu.parallel.sharding import (
    ShardingRules,
    batch_sharding,
    compile_partition_rules,
    leaf_partition_spec,
    make_sharding_rules,
    sharding_tree,
)

__all__ = [
    "build_mesh",
    "initialize_distributed",
    "local_device_count",
    "ShardingRules",
    "batch_sharding",
    "compile_partition_rules",
    "leaf_partition_spec",
    "make_sharding_rules",
    "sharding_tree",
    "pipeline",
    "stack_stage_params",
]

"""Sharding rules: the ZeRO-1/2/3 ladder as NamedSharding presets.

This module is the TPU-native replacement for the reference's sharding
extensions (stoke/extensions.py:81-376 — fairscale OSS, ShardedDataParallel,
FullyShardedDataParallel).  Where fairscale hand-implements broadcast /
reduce-scatter / all-gather schedules in CUDA streams, here each tier is just
a *placement rule* — which pytrees (params / grads / optimizer state) are
sharded over the mesh ``data`` axis — and XLA's GSPMD pass derives the
collectives (arxiv 2004.13336 "Automatic Cross-Replica Sharding of Weight
Update"; SURVEY.md §7):

- tier none (plain DP, reference extensions.py:151-216):
    params/grads/opt replicated; XLA all-reduces grads.
- tier oss  (ZeRO-1, reference extensions.py:81-141):
    optimizer state sharded → weight-update sharding; XLA turns the grad
    all-reduce into reduce-scatter + all-gather of updated params.
- tier sddp (ZeRO-2, reference extensions.py:219-286):
    + gradient accumulator sharded → the combine is a true reduce-scatter and
    the fp32 grad buffer takes 1/N memory.
- tier fsdp (ZeRO-3, reference extensions.py:289-376):
    + parameters sharded → all-gather before use, scheduled by XLA.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from stoke_tpu.configs import FSDPConfig, OSSConfig, SDDPConfig, ShardingOptions


def leaf_partition_spec(
    shape: tuple,
    axis_name: str,
    axis_size: int,
    min_size: int = 0,
    preference: str = "largest",
) -> P:
    """Choose the PartitionSpec for one array: shard one dimension over the
    data axis if profitable, else replicate.

    Mirrors the role of fairscale's parameter flatten-and-chunk (FSDP
    ``flatten_parameters``, reference configs.py:672) without the flattening:
    XLA shards at array granularity, so we pick the dimension — the largest
    one divisible by the axis size ("largest", default) or dim 0 when
    divisible ("first").  Arrays smaller than ``min_size`` elements stay
    replicated (collective latency beats memory savings; reference FSDP-style
    min-param bucketing).
    """
    if axis_size <= 1 or not shape:
        return P()
    if int(np.prod(shape)) < max(min_size, axis_size):
        return P()
    dims = range(len(shape))
    if preference == "first":
        # dim 0 when divisible, else replicate (documented semantics)
        pick = 0 if shape[0] % axis_size == 0 else None
    else:
        divisible = [d for d in dims if shape[d] % axis_size == 0]
        pick = max(divisible, key=lambda d: shape[d], default=None)
    if pick is None:
        return P()
    spec = [None] * len(shape)
    spec[pick] = axis_name
    return P(*spec)


def _path_str(path) -> str:
    return "/".join(
        str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", p))))
        for p in path
    )


def sharding_tree(
    tree_shapes: Any,
    mesh: Mesh,
    spec_fn: Callable[[tuple], P],
    overrides: Optional[Any] = None,
    strict_overrides: bool = True,
) -> Any:
    """Map a pytree of arrays/ShapeDtypeStructs to a pytree of NamedShardings.

    ``overrides`` is a sequence of compiled ``(regex, P)`` pairs matched
    against the '/'-joined leaf path; first match wins over ``spec_fn``
    (the tensor-parallelism hook, see PartitionRulesConfig).  With
    ``strict_overrides=False`` a rank mismatch falls back to ``spec_fn``
    instead of raising (used for optimizer-state trees, where e.g.
    factored-statistics leaves share the parameter's path but not its rank).
    """

    def _spec_for(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if overrides:
            p = _path_str(path)
            for rx, spec in overrides:
                if rx.search(p):
                    entries = tuple(spec)
                    if entries and entries[-1] is Ellipsis:
                        # variadic rule: pad the remaining dims with None
                        # (e.g. ("stage", ...) for stage-stacked trees whose
                        # leaves have mixed ranks)
                        head = entries[:-1]
                        if len(head) > len(shape):
                            if strict_overrides:
                                raise ValueError(
                                    f"Stoke -- partition rule {rx.pattern!r} "
                                    f"needs at least {len(head)} dims but "
                                    f"parameter {p} has shape {shape}"
                                )
                            break
                        entries = head + (None,) * (len(shape) - len(head))
                    if len(entries) != len(shape):
                        if strict_overrides:
                            raise ValueError(
                                f"Stoke -- partition rule {rx.pattern!r} has "
                                f"{len(entries)} entries but parameter {p} has "
                                f"shape {shape}"
                            )
                        break
                    return NamedSharding(mesh, P(*entries))
        return NamedSharding(mesh, spec_fn(shape))

    return jax.tree_util.tree_map_with_path(_spec_for, tree_shapes)


def batch_sharding(mesh: Optional[Mesh], axis_name: str = "data"):
    """NamedSharding placing the global batch split over the data axis
    (replaces per-rank DataLoader slices + ``place_data_on_gpu``,
    reference stoke/utils.py:39-80; SURVEY.md §3.3)."""
    if mesh is None:
        return None

    def _spec(shape):
        if not shape:
            return P()
        if shape[0] % mesh.shape[axis_name] != 0:
            return P()
        return P(axis_name)

    class _BatchShardingFactory:
        def for_leaf(self, shape):
            return NamedSharding(mesh, _spec(tuple(shape)))

    return _BatchShardingFactory()


@dataclass
class ShardingRules:
    """Placement rules for one run: which state pytrees shard over ``data``.

    ``None`` spec-fn means "replicated everywhere".  Built once by
    :func:`make_sharding_rules` from the validated status flags and consumed
    by the engine when it pins ``in_shardings``/``out_shardings`` on the
    compiled steps.  ``overrides`` are compiled path-regex partition rules
    (tensor parallelism) that take precedence over the tier placement for
    params, grads, AND matching optimizer-state leaves.
    """

    mesh: Optional[Mesh]
    axis_name: str
    param_spec: Callable[[tuple], P]
    grad_spec: Callable[[tuple], P]
    opt_spec: Callable[[tuple], P]
    overrides: Optional[Any] = None
    #: the tier these rules were built from — consumed by the gradient
    #: transport factory (ISSUE 8) to resolve ``CommConfig.shard_updates``'s
    #: auto default and to know whether updated params all-gather at the
    #: apply boundary (replicated-param tiers) or stay sharded (fsdp)
    tier: ShardingOptions = ShardingOptions.none

    def param_shardings(self, tree_shapes):
        return sharding_tree(tree_shapes, self.mesh, self.param_spec, self.overrides)

    def grad_shardings(self, tree_shapes):
        return sharding_tree(tree_shapes, self.mesh, self.grad_spec, self.overrides)

    def opt_shardings(self, tree_shapes):
        return sharding_tree(
            tree_shapes, self.mesh, self.opt_spec, self.overrides,
            strict_overrides=False,
        )

    def replicated(self):
        return NamedSharding(self.mesh, P())


def place_global_tree(tree: Any, shardings: Any) -> Any:
    """Place host-resident pytree leaves onto (possibly multi-host) global
    shardings.

    Single-process this is plain ``jax.device_put``.  Multi-controller JAX
    forbids ``device_put`` of a host array onto a sharding spanning
    non-addressable devices ("cross-host reshard"); there, each process
    feeds its addressable shards from its full host copy via
    ``jax.make_array_from_callback`` (every process holds the same full
    value — the contract for initial state, replicated scalars, and
    consolidated-checkpoint restores; the reference's per-rank
    ``torch.load`` + broadcast plays the same role, io_ops.py:551-623).

    ``shardings`` is either a pytree matching ``tree`` or one sharding
    applied to every leaf.
    """
    if jax.process_count() == 1:
        return jax.device_put(tree, shardings)

    def _leaf(x, sh):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            # already a global array: reshard computationally (same device
            # set); fetching it to host is impossible by definition
            return jax.device_put(x, sh)
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])

    if isinstance(shardings, jax.sharding.Sharding):
        return jax.tree_util.tree_map(lambda x: _leaf(x, shardings), tree)
    return jax.tree_util.tree_map(_leaf, tree, shardings)


def compile_partition_rules(rules) -> Optional[list]:
    """Compile (regex, spec-tuple) pairs into (pattern, entries-tuple).

    A trailing ``...`` (or the string ``"..."``, for YAML) makes the rule
    variadic: remaining dims are replicated — for trees whose leaves have
    mixed ranks (e.g. stage-stacked pipeline parameters)."""
    import re

    if not rules:
        return None
    compiled = []
    for rx, spec in rules:
        entries = tuple(
            Ellipsis if e is Ellipsis or e == "..." else e for e in spec
        )
        compiled.append((re.compile(rx), entries))
    return compiled


def make_sharding_rules(
    tier: ShardingOptions,
    mesh: Optional[Mesh],
    axis_name: str,
    oss_config: OSSConfig,
    sddp_config: SDDPConfig,
    fsdp_config: FSDPConfig,
    partition_rules=None,
) -> Optional[ShardingRules]:
    """Build the tier's placement rules (the ladder table in the module
    docstring).  Returns None when there is no mesh (single-device).
    ``partition_rules`` are user (path-regex → spec) overrides — the tensor
    parallelism hook (PartitionRulesConfig)."""
    if mesh is None:
        return None
    overrides = compile_partition_rules(partition_rules)
    # a mesh without the dp axis (e.g. pure pipeline: axes=("stage",)) is
    # legal — state is replicated across it and only partition rules shard
    size = mesh.shape.get(axis_name, 1) if hasattr(mesh.shape, "get") else (
        mesh.shape[axis_name] if axis_name in mesh.axis_names else 1
    )
    repl: Callable[[tuple], P] = lambda shape: P()
    shard_opt = lambda shape: leaf_partition_spec(
        shape, axis_name, size, oss_config.min_shard_size, "largest"
    )
    shard_grad = lambda shape: leaf_partition_spec(
        shape, axis_name, size, sddp_config.min_shard_size, "largest"
    )
    shard_param = lambda shape: leaf_partition_spec(
        shape,
        axis_name,
        size,
        fsdp_config.min_weight_size,
        fsdp_config.shard_axis_preference,
    )
    if tier is ShardingOptions.none:
        return ShardingRules(mesh, axis_name, repl, repl, repl, overrides, tier)
    if tier is ShardingOptions.oss:
        return ShardingRules(mesh, axis_name, repl, repl, shard_opt, overrides, tier)
    if tier is ShardingOptions.sddp:
        return ShardingRules(
            mesh, axis_name, repl, shard_grad, shard_opt, overrides, tier
        )
    if tier is ShardingOptions.fsdp:
        # FSDP: params/grads/opt all follow the *param* placement so the
        # update is fully local (reference FSDP shards the flat param and
        # derives grad/opt shards from it, extensions.py:289-376).
        return ShardingRules(
            mesh, axis_name, shard_param, shard_param, shard_param, overrides,
            tier,
        )
    raise ValueError(f"unknown sharding tier {tier}")

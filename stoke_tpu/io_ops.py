"""Unified checkpoint save/load across sharding tiers.

TPU-native re-design of the reference IO mixins (stoke/io_ops.py:20-746).
The reference needs four strategies (BaseStokeIO/DDPIO/HorovodIO/DeepspeedIO)
because each backend owns state differently (FSDP shard gathering
io_ops.py:569-600, OSS consolidation :584, DeepSpeed engine checkpoints
:389-544).  Here state is a pytree with *declared* shardings, so there are
exactly two layouts:

- ``consolidated``: gather to host and write one portable file set (numpy
  arrays + JSON metadata) — the reference's rank-0 ``torch.save`` path
  (io_ops.py:551-623).  Works across topology changes.
- ``sharded``: every host writes its shards via orbax/tensorstore — the
  reference's DeepSpeed sharded path (io_ops.py:389-483), but
  restorable onto any topology because shardings are re-applied from the
  *target* state at load time (the FSDP shard-extraction of the reference,
  io_ops.py:298-306, is subsumed by "load into the declared shardings").

The payload schema mirrors the reference exactly (io_ops.py:224-236):
counters {backward_step, grad_accum_step, optimizer_step}, the status dict,
model/optimizer/scaler state, and user extras.  Tag scheme:
``stoke-{name}-backward-step-{n}`` (reference io_ops.py:49-87).
"""

from __future__ import annotations

import json
import os
import pickle
import re
import shutil
import threading
from typing import Any, Dict, Optional, Tuple

_ASYNC_SAVES: list = []  # in-flight background save threads
_ASYNC_ERRORS: list = []  # exceptions raised by background saves (surfaced in wait_for_saves)
_INFLIGHT_TAGS: set = set()  # tag dirs being written by async saves (prune must skip)

import jax
import numpy as np

from stoke_tpu.telemetry.tracing import trace_span

from stoke_tpu.configs import CheckpointConfig, CheckpointFormat
from stoke_tpu.utils.printing import make_folder, unrolled_print

_TAG_RE = re.compile(r"^stoke-(?P<name>.+)-backward-step-(?P<step>\d+)$")


def checkpoint_tag(name: str, backward_step: int) -> str:
    """Reference tag scheme ``stoke-{name}-backward-step-{n}.pt``
    (io_ops.py:49-87); here a directory."""
    return f"stoke-{name}-backward-step-{backward_step}"


def _is_multiprocess() -> bool:
    return jax.process_count() > 1


def _writer_rank(config: CheckpointConfig) -> int:
    """The process that writes consolidated payloads + metadata (reference
    ``DDPIO._save_rank`` / OSS ``consolidate_state_dict(recipient_rank)``,
    io_ops.py:551-623).  Modulo process count so a config written for a
    larger pod degrades to a valid rank instead of never writing."""
    return int(config.save_rank) % max(jax.process_count(), 1)


def _gather_to_host(tree: Any) -> Any:
    """Device pytree → host numpy pytree, gathering shards across hosts when
    needed (the consolidation step the reference implements per-backend,
    io_ops.py:569-600)."""
    if _is_multiprocess():
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(tree, tiled=True)
    return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), tree)


def _flat_arrays(tree: Any):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def _save_consolidated(
    tag_dir: str, state: Dict[str, Any], writer: int = 0
) -> None:
    """One ``.npz`` per state tree, leaves in flatten order (restore relies on
    the target structure, so no treedef serialization is needed).  Multi-host:
    every process gathers (a collective), only the ``writer`` process (config
    ``save_rank``) writes."""
    for key, tree in state.items():
        host = _gather_to_host(tree)
        if jax.process_index() != writer:
            continue
        leaves, _ = _flat_arrays(host)
        np.savez(
            os.path.join(tag_dir, f"{key}.npz"),
            **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)},
        )


def _load_consolidated(tag_dir: str, key: str, like: Any) -> Any:
    with np.load(os.path.join(tag_dir, f"{key}.npz")) as data:
        leaves_like, treedef = _flat_arrays(like)
        n = len(data.files)
        if n != len(leaves_like):
            raise ValueError(
                f"Stoke -- checkpoint {key} has {n} leaves; current state has "
                f"{len(leaves_like)} (model/optimizer structure changed?)"
            )
        loaded = [data[f"leaf_{i}"] for i in range(n)]
    from stoke_tpu.parallel.sharding import place_global_tree

    placed = []
    for arr, ref in zip(loaded, leaves_like):
        if hasattr(ref, "sharding"):
            placed.append(
                place_global_tree(arr.astype(ref.dtype), ref.sharding)
            )
        else:
            placed.append(arr)
    return jax.tree_util.tree_unflatten(treedef, placed)


def _np_dtype(name: str) -> np.dtype:
    """Dtype from its string name, including the ml_dtypes family numpy
    itself cannot resolve (bfloat16, fp8 variants) — those are looked up on
    the jax.numpy namespace."""
    try:
        return np.dtype(name)
    except TypeError:
        import jax.numpy as jnp

        return np.dtype(getattr(jnp, name))


def _staged_files(key: str, rank: int) -> Tuple[str, str]:
    """(npz, json) file names of one process's staged payload of ``key``."""
    return (
        f"{key}.staged.rank{rank}.npz",
        f"{key}.staged.rank{rank}.json",
    )


def _write_staged_payload(
    tag_dir: str, key: str, rank: int, records: list
) -> None:
    """Write one resolved :class:`~stoke_tpu.offload.StagedSnapshot` as this
    process's shard file pair: raw-byte npz (uint8 spill, the
    DiskOptimizerStore convention — .npy silently degrades ml_dtypes) plus a
    json index mapping each leaf's shards back to normalized global-index
    slices.  Both writes are tmp+rename atomic and the INDEX lands last, so
    a killed writer leaves an index-less (detectably partial) payload."""
    npz_name, json_name = _staged_files(key, rank)
    arrays: Dict[str, np.ndarray] = {}
    index: Dict[str, Any] = {"version": 1, "rank": rank, "leaves": []}
    for i, (kind, rec) in enumerate(records):
        if kind == "static":
            arrays[f"leaf{i}_static"] = np.asarray(rec)
            index["leaves"].append({"kind": "static"})
            continue
        shape, dtype, shards = rec
        entry = {
            "kind": "array",
            "shape": list(shape),
            "dtype": np.dtype(dtype).name,
            "shards": [],
        }
        for j, (norm_idx, data, shard_shape) in enumerate(shards):
            name = f"leaf{i}_shard{j}"
            flat = np.ascontiguousarray(data).reshape(-1)
            arrays[name] = flat.view(np.uint8) if flat.size else flat.astype(
                np.uint8
            )
            entry["shards"].append({
                "name": name,
                "index": [list(t) for t in norm_idx],
                "shape": list(shard_shape),
            })
        index["leaves"].append(entry)
    npz_path = os.path.join(tag_dir, npz_name)
    # ".tmp" suffix is load-bearing: manifest digesting skips in-flight
    # writes by exactly that suffix (resilience._walk_files) — another
    # rank's manifest must never list this file until the rename lands
    tmp = npz_path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, npz_path)
    json_path = os.path.join(tag_dir, json_name)
    tmp = json_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(index, f)
    os.replace(tmp, json_path)


def _load_staged(tag_dir: str, key: str, like: Any, processes: int) -> Any:
    """Reassemble one state tree from EVERY process's staged shard files
    onto the CURRENT layout.  Shards are written against normalized
    global-index slices, so reassembly is topology-free by construction —
    a v4-32 save restores onto a v4-16 mesh (or any other) because the
    target shardings come from ``like``, not from the writer's mesh (the
    elastic-resume property, ISSUE 14)."""
    from stoke_tpu.parallel.sharding import place_global_tree

    per_rank = []
    for r in range(max(processes, 1)):
        npz_name, json_name = _staged_files(key, r)
        with open(os.path.join(tag_dir, json_name)) as f:
            index = json.load(f)
        data = np.load(os.path.join(tag_dir, npz_name))
        per_rank.append((index, data))
    leaves_like, treedef = _flat_arrays(like)
    n = len(per_rank[0][0]["leaves"])
    if n != len(leaves_like):
        raise ValueError(
            f"Stoke -- staged checkpoint {key} has {n} leaves; current "
            f"state has {len(leaves_like)} (model/optimizer structure "
            f"changed?)"
        )
    placed = []
    for i, ref in enumerate(leaves_like):
        entry = per_rank[0][0]["leaves"][i]
        if entry["kind"] == "static":
            placed.append(per_rank[0][1][f"leaf{i}_static"])
            continue
        shape = tuple(entry["shape"])
        dtype = _np_dtype(entry["dtype"])
        out = np.zeros(shape, dtype)
        for index, data in per_rank:
            for shard in index["leaves"][i]["shards"]:
                raw = data[shard["name"]]
                shard_shape = tuple(shard["shape"])
                value = (
                    raw.view(dtype).reshape(shard_shape)
                    if raw.size
                    else np.zeros(shard_shape, dtype)
                )
                sl = tuple(
                    slice(s, e, st) for s, e, st in shard["index"]
                )
                out[sl] = value
        if hasattr(ref, "sharding"):
            placed.append(
                place_global_tree(
                    out.astype(ref.dtype, copy=False), ref.sharding
                )
            )
        else:
            placed.append(out)
    for _index, data in per_rank:
        data.close()
    return jax.tree_util.tree_unflatten(treedef, placed)


def _orbax_checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _save_sharded(tag_dir: str, state: Dict[str, Any]) -> None:
    ckpt = _orbax_checkpointer()
    for key, tree in state.items():
        ckpt.save(os.path.join(tag_dir, f"{key}.orbax"), tree)
    ckpt.wait_until_finished()


def _save_sharded_async(tag_dir: str, state: Dict[str, Any]) -> list:
    """Kick off orbax async sharded writes; returns the checkpointer handles.

    ``AsyncCheckpointer.save`` copies device shards to host ON THE CALLING
    (main) thread, then serializes + writes in orbax's own background
    machinery — including the cross-process commit coordination (the
    distributed KV-store barriers ride gRPC, not XLA collectives, so they
    are safe off the main thread).  Every process must create/save in the
    same order so the barrier keys line up."""
    import orbax.checkpoint as ocp

    handles = []
    for key, tree in state.items():
        c = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        c.save(os.path.join(tag_dir, f"{key}.orbax"),
               args=ocp.args.StandardSave(tree))
        handles.append(c)
    return handles


def _load_sharded(tag_dir: str, key: str, like: Any) -> Any:
    ckpt = _orbax_checkpointer()
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if hasattr(x, "sharding")
        else x,
        like,
    )
    return ckpt.restore(os.path.join(tag_dir, f"{key}.orbax"), abstract)


def save_checkpoint(
    path: str,
    name: str,
    variables: Any,
    opt_state: Any,
    scaler_state: Any,
    counters: Dict[str, int],
    status: Dict[str, Any],
    extras: Optional[Dict[str, Any]],
    config: CheckpointConfig,
    backward_step: int,
    grad_buf: Any = None,
    manifest: bool = False,
    topology: Optional[Dict[str, Any]] = None,
    chaos: Any = None,
    on_durable: Optional[Any] = None,
) -> str:
    """Write one logical checkpoint; returns the tag directory path.

    Reference flow (io_ops.py:160-243 + per-backend wrappers :551-703):
    barrier → gather/consolidate → write (the ``save_rank`` writer for
    consolidated, all ranks for sharded) → barrier.  Metadata
    (counters/status/extras) is written by the ``save_rank`` writer only
    (reference ``DDPIO._save_rank``, io_ops.py:551-623).  ``grad_buf``
    (the partial accumulation window) is saved
    too so a mid-window resume loses no gradient mass — the reference cannot
    do this (torch ``.grad`` is not in ``state_dict``).

    ``manifest=True`` (ISSUE 7): after ``meta.json``, the writer rank adds
    a ``manifest.json`` of per-file sha256 digests over the completed tag —
    the integrity record ``Stoke.resume()`` validates against before
    trusting a checkpoint (corrupt/partial tags are quarantined, never
    loaded).  Written LAST on both the sync and async paths, so a tag with
    a manifest is a tag whose write finished.

    ``topology`` (ISSUE 14): the saving run's topology/sharding descriptor
    (mesh shape, process count, tier, ``shard_updates``, comm bucket
    layout) embedded in the manifest — what ``Stoke.resume()`` reads to
    re-shard state onto a DIFFERENT mesh and to quarantine genuinely
    incompatible checkpoints with a remedy named.

    ``config.offload_staging`` (ISSUE 14 tentpole a): the async
    consolidated save stages device→host through
    ``offload.StagedSnapshot`` instead of completing a blocking gather on
    the main thread — the step path pays one copy-program dispatch, the
    transfers land off the critical path, and EVERY process writes its own
    ``<key>.staged.rank<N>.npz`` shard files (no collective anywhere on
    the save path).  ``meta.json`` records the staged layout so load and
    the resume-time validator know how many rank files completeness
    requires.

    ``chaos`` (ISSUE 14 satellite): the run's ``ChaosInjector`` — its
    ``kill_during_save`` hook fires from the background writer AFTER the
    payload and BEFORE ``meta.json``, proving a mid-save death leaves a
    detectably partial (never loadable, always quarantined) tag.

    ``on_durable`` (ISSUE 14 satellite): zero-arg callback invoked once
    THIS save's write has fully landed — synchronously for sync saves,
    from the background thread after ``meta.json`` for async ones.  The
    facade's lost-goodput accounting hangs off it: a save only counts as
    "the last durable save" when its own write succeeded, never at
    dispatch (an in-flight or failed save must keep counting as lost).
    """
    root = make_folder(path)
    tag = checkpoint_tag(name, backward_step)
    tag_dir = os.path.join(root, tag)
    is_async = bool(config.async_save)
    if is_async:
        # claim the tag BEFORE creating the dir: a concurrently finishing
        # earlier async save's _prune_old must never classify this (still
        # meta-less) dir as a stale leftover during the gather window.
        # Released on ANY failure before the background thread takes over
        # (the thread then owns the release).
        _INFLIGHT_TAGS.add(tag_dir)
    writer = _writer_rank(config)
    try:
        if jax.process_index() == writer:
            os.makedirs(tag_dir, exist_ok=True)
        _barrier()
    except BaseException:
        _INFLIGHT_TAGS.discard(tag_dir)
        raise
    state = {
        "variables": variables,
        "opt_state": opt_state,
        "scaler_state": scaler_state,
    }
    if grad_buf is not None:
        state["grad_buf"] = grad_buf
    staged_meta: Optional[Dict[str, Any]] = None

    def _write_meta_files(fmt_value: str) -> None:
        """meta.json + extras.pkl — the ``save_rank`` writer only; shared by
        the sync and async paths so the metadata schema can never drift
        between them."""
        if jax.process_index() != writer:
            return
        # extras BEFORE meta.json: meta is the tag's "loadable" marker
        # (verify_checkpoint treats a meta-less tag as a partial write), so
        # a hard kill between the two files must leave the tag UNloadable —
        # the reverse order would let resume silently restore without the
        # rng/EMA/EF-residual extras and break bit-identical resumption
        if extras:
            with open(os.path.join(tag_dir, "extras.pkl"), "wb") as f:
                pickle.dump(extras, f)
        meta = {
            "format": fmt_value,
            "counters": counters,
            "status": status,
            "name": name,
        }
        if staged_meta is not None:
            # staged layout marker (ISSUE 14): load + the resume-time
            # validator derive "which rank files must exist" from this —
            # a kill that stranded another rank's shard file mid-write
            # must read as a partial tag, not a short checkpoint
            meta["staged"] = staged_meta
        with open(os.path.join(tag_dir, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2, default=str)
        if manifest:
            # integrity digests over the finished tag (ISSUE 7) — shared
            # by the sync and async paths like the meta schema above, so
            # the manifest can never claim files a crashed write lost
            from stoke_tpu.resilience import write_manifest

            extra = {"backward_step": backward_step, "name": name}
            if topology is not None:
                # topology/sharding descriptor (ISSUE 14): the record
                # elastic resume re-shards against
                extra["topology"] = topology
            write_manifest(tag_dir, extra=extra)

    def _write_meta():
        if jax.process_index() == writer:
            _write_meta_files(config.format.value)
            _prune_old(root, name, config.max_to_keep)
            unrolled_print(f"Saved checkpoint {tag_dir}")

    if is_async:
        # Async save: anything touching DEVICE arrays or XLA collectives
        # happens HERE, synchronously on the main thread — the compiled
        # steps donate (invalidate) state buffers, and multi-host gather
        # collectives cannot run off-thread.  Only serialization + disk
        # (and orbax's gRPC commit coordination) runs in the background.
        # meta.json is written last — and, multi-process, only after the
        # global commit — so a crash mid-save never leaves a loadable
        # partial tag (load requires meta.json).
        is_writer = jax.process_index() == writer
        if config.format is CheckpointFormat.sharded:
            # orbax AsyncCheckpointer: device→host copy on this thread,
            # sharded tensorstore writes + cross-host commit in background
            try:
                # traced: the async save's main-thread (step-path) cost
                with trace_span("stoke/ckpt_save", track="io",
                                attrs={"tag": tag, "async": True}):
                    handles = _save_sharded_async(tag_dir, state)
            except BaseException:
                _INFLIGHT_TAGS.discard(tag_dir)
                raise

            def _write_payload():
                for h in handles:
                    # returns after THIS process's writes are durable and
                    # the cross-process commit barrier has passed — on
                    # process 0 that makes meta.json a global completeness
                    # marker.  close() releases the checkpointer's
                    # background machinery (a fresh one is built per save;
                    # leaving them open leaks threads across a long run)
                    h.wait_until_finished()
                    h.close()

            fmt_value = CheckpointFormat.sharded.value
        elif getattr(config, "offload_staging", False):
            # zero-stall staged save (ISSUE 14 tentpole a): the main
            # thread issues the decoupling copy + async host transfers and
            # returns — no gather, no collective.  The background thread
            # resolves the landed shards and writes THIS process's shard
            # files; every process writes its own, so the layout needs no
            # cross-host coordination beyond the meta-side completeness
            # marker recorded below.
            from stoke_tpu import offload

            rank = jax.process_index()
            nproc = max(jax.process_count(), 1)
            try:
                # traced: the staged save's main-thread (step-path) cost —
                # ONE copy-program dispatch for the whole state dict.  One
                # snapshot per SAVE, not per state tree: the double buffer
                # bounds in-flight SAVES at two, so staging a save's later
                # trees can never force-resolve its own earlier trees on
                # the main thread (which would be the gather stall under a
                # different name).
                with trace_span("stoke/ckpt_save", track="io",
                                attrs={"tag": tag, "async": True,
                                       "staged": True}):
                    staged_snap = offload.stage_tree(state)
            except BaseException:
                _INFLIGHT_TAGS.discard(tag_dir)
                raise
            staged_meta = {"processes": nproc, "keys": sorted(state)}
            # flatten order of the combined dict is key-sorted; each key's
            # leaves are a contiguous record slice in that order
            key_counts = [
                (k, len(jax.tree_util.tree_leaves(state[k])))
                for k in sorted(state)
            ]

            def _write_payload():
                _treedef, records = staged_snap.resolve()
                off = 0
                for key, n in key_counts:
                    _write_staged_payload(
                        tag_dir, key, rank, records[off:off + n]
                    )
                    off += n

            fmt_value = CheckpointFormat.consolidated.value
        else:
            # consolidated: gather (collective, main thread) → proc-0 write
            try:
                # traced: the async save's main-thread (step-path) cost
                with trace_span("stoke/ckpt_save", track="io",
                                attrs={"tag": tag, "async": True}):
                    host_state = {
                        k: _gather_to_host(v) for k, v in state.items()
                    }
            except BaseException:
                _INFLIGHT_TAGS.discard(tag_dir)  # claim released on gather failure
                raise

            def _write_payload():
                if not is_writer:
                    return
                for key, tree in host_state.items():
                    leaves, _ = _flat_arrays(tree)
                    np.savez(
                        os.path.join(tag_dir, f"{key}.npz"),
                        **{f"leaf_{i}": np.asarray(l)
                           for i, l in enumerate(leaves)},
                    )

            fmt_value = CheckpointFormat.consolidated.value

        def _bg():
            try:
                _write_payload()
                if chaos is not None:
                    # kill_during_save injector (ISSUE 14 satellite):
                    # SIGKILL between payload and meta.json — the
                    # half-staged state a preempted host really leaves
                    chaos.on_async_payload(tag_dir)
                _write_meta_files(fmt_value)
                # meta.json is on disk: this tag is now a complete, loadable
                # checkpoint — leave the in-flight set BEFORE pruning so it
                # counts toward its own keep window
                _INFLIGHT_TAGS.discard(tag_dir)
                if on_durable is not None:
                    try:
                        on_durable()
                    except Exception:
                        pass  # accounting must never fail a landed save
                if is_writer:
                    _prune_old(root, name, config.max_to_keep)
                    unrolled_print(f"Saved checkpoint {tag_dir} (async)")
            except BaseException as e:  # surfaced by wait_for_saves()
                # write-phase failure → remove the partial tag (it can never
                # load without meta.json).  A failure AFTER meta.json exists
                # (e.g. a transient error inside _prune_old) leaves the
                # complete, loadable checkpoint in place.
                if is_writer and not os.path.exists(
                    os.path.join(tag_dir, "meta.json")
                ):
                    shutil.rmtree(tag_dir, ignore_errors=True)
                _ASYNC_ERRORS.append((tag_dir, e))
            finally:
                _INFLIGHT_TAGS.discard(tag_dir)

        t = threading.Thread(target=_bg, name=f"stoke-save-{tag}", daemon=False)
        _ASYNC_SAVES.append(t)
        try:
            t.start()
        except BaseException:
            _ASYNC_SAVES.remove(t)
            _INFLIGHT_TAGS.discard(tag_dir)
            raise
        return tag_dir
    # the save span (ISSUE 10): the synchronous write path end-to-end —
    # gather, payload, metadata, barrier.  The async path above is traced
    # per-phase instead (its main-thread cost is the gather; the
    # background write is off the step path by design).
    with trace_span("stoke/ckpt_save", track="io", attrs={"tag": tag}):
        if config.format is CheckpointFormat.consolidated:
            _save_consolidated(tag_dir, state, writer)
        else:
            _save_sharded(tag_dir, state)
        _write_meta()
        _barrier()
    if on_durable is not None:
        try:
            on_durable()
        except Exception:
            pass
    return tag_dir


def wait_for_saves() -> None:
    """Block until all in-flight async checkpoint saves complete (call
    before exiting or before loading a just-saved checkpoint).

    Multi-process, ends with a global barrier: a non-zero process's
    background thread can finish before process 0 has written ``meta.json``,
    so without the barrier "my threads are done" would not mean "the
    checkpoint is loadable".  The barrier runs before errors are raised so
    a failing process never strands its peers mid-barrier.

    Raises on background-save failure (disk full, serialization error, ...)
    rather than silently dropping it — a checkpoint that was never written
    must not look saved (ADVICE r1: io_ops medium).  EVERY failed tag dir
    is named in the message (ISSUE 7 satellite: an operator deciding which
    checkpoints are trustworthy needs the full casualty list, not the first
    failure with "+2 more"); the first underlying exception chains as the
    cause and the rest are summarized inline."""
    with trace_span("stoke/ckpt_wait", track="io"):
        # staged landing buffers FIRST (ISSUE 14): an offload-staged save
        # still mid-flight holds device-side snapshot copies whose host
        # transfers must land before any synchronous gather this caller
        # runs next (the emergency save's).  Thread joins alone would
        # cover it eventually, but the explicit drain pins the ordering:
        # staging resolves, then writer threads, then the barrier.
        from stoke_tpu.offload import drain_staged

        drain_staged()
        while _ASYNC_SAVES:
            _ASYNC_SAVES.pop().join()
        _barrier()
    if _ASYNC_ERRORS:
        failures = list(_ASYNC_ERRORS)
        _ASYNC_ERRORS.clear()
        _, first_err = failures[0]
        detail = "; ".join(
            f"{tag_dir} ({type(err).__name__}: {err})"
            for tag_dir, err in failures
        )
        raise RuntimeError(
            f"Stoke -- {len(failures)} async checkpoint save"
            f"{'s' if len(failures) > 1 else ''} failed: {detail}"
        ) from first_err


def _prune_old(root: str, name: str, max_to_keep: Optional[int]) -> None:
    """Keep the newest N tags (by backward step) for this name.

    Tags this process is still writing (``_INFLIGHT_TAGS``; async saves
    write ``meta.json`` last) are never pruned — deleting one mid-write
    would corrupt a concurrent save.  Meta-less tags that are NOT in flight
    are leftovers from a crashed/failed save and are pruned like any other
    old tag (they can never load)."""
    if not max_to_keep:
        return
    tags, stale = [], []
    for entry in os.listdir(root):
        m = _TAG_RE.match(entry)
        if m and m.group("name") == name:
            if os.path.join(root, entry) in _INFLIGHT_TAGS:
                continue
            if not os.path.exists(os.path.join(root, entry, "meta.json")):
                stale.append(entry)  # crashed/failed leftover, never loadable
                continue
            tags.append((int(m.group("step")), entry))
    tags.sort()
    # only loadable tags count toward the keep window (a crashed leftover
    # must never displace a loadable checkpoint)
    for entry in stale:
        shutil.rmtree(os.path.join(root, entry), ignore_errors=True)
    for _, entry in tags[:-max_to_keep]:
        shutil.rmtree(os.path.join(root, entry), ignore_errors=True)


def _latest_tag(root: str, name: Optional[str]) -> Optional[str]:
    """Newest tag by backward step, scoped to ``name`` when given (so two
    runs sharing a directory never load each other's state)."""
    best = None
    for entry in os.listdir(root):
        m = _TAG_RE.match(entry)
        if m and (name is None or m.group("name") == name):
            step = int(m.group("step"))
            if best is None or step > best[0]:
                best = (step, entry)
    return best[1] if best else None


def _barrier() -> None:
    # instrumented (ISSUE 5 satellite): checkpoint-coordination waits land
    # in sync/barrier_wait_s of every live telemetry registry — before
    # this, cross-process sync time around IO was invisible to the
    # goodput ledger and un-attributable to the straggler host
    if _is_multiprocess():
        from jax.experimental import multihost_utils

        from stoke_tpu.telemetry.fleet import timed_sync

        with timed_sync("ckpt"):
            multihost_utils.sync_global_devices("stoke_ckpt")


def load_checkpoint(
    path: str,
    tag: Optional[str],
    variables_like: Any,
    opt_state_like: Any,
    scaler_like: Any,
    config: CheckpointConfig,
    name: Optional[str] = None,
    grad_buf_like: Any = None,
) -> Dict[str, Any]:
    """Load a checkpoint onto the CURRENT sharding layout.

    ``tag=None`` loads the newest tag under ``path`` (scoped to ``name`` when
    given).  The on-disk format is read from ``meta.json`` (a consolidated
    checkpoint can be loaded by a sharded run and vice versa — the reference
    cannot do this across backends; SURVEY.md §7 hard part #4).
    """
    root = os.path.abspath(os.path.expanduser(path))
    if tag is None:
        tag = _latest_tag(root, name)
        if tag is None:
            raise FileNotFoundError(f"Stoke -- no checkpoints found under {root}")
    tag_dir = os.path.join(root, tag)
    with open(os.path.join(tag_dir, "meta.json")) as f:
        meta = json.load(f)
    fmt = CheckpointFormat(meta["format"])
    staged = meta.get("staged")
    if staged:
        # offload-staged layout (ISSUE 14): per-process shard files keyed
        # by normalized global indices — reassembled onto the CURRENT
        # shardings, so the writer's topology is irrelevant at load
        import functools

        loader = functools.partial(
            _load_staged, processes=int(staged.get("processes", 1))
        )
    elif fmt is CheckpointFormat.consolidated:
        loader = _load_consolidated
    else:
        loader = _load_sharded
    payload = {
        "variables": loader(tag_dir, "variables", variables_like),
        "opt_state": loader(tag_dir, "opt_state", opt_state_like),
        "scaler_state": loader(tag_dir, "scaler_state", scaler_like),
        "counters": meta["counters"],
        "status": meta["status"],
        "grad_buf": None,
    }
    has_buf = (
        os.path.exists(os.path.join(tag_dir, "grad_buf.npz"))
        or os.path.exists(os.path.join(tag_dir, "grad_buf.orbax"))
        or os.path.exists(
            os.path.join(tag_dir, _staged_files("grad_buf", 0)[0])
        )
    )
    if grad_buf_like is not None and has_buf:
        payload["grad_buf"] = loader(tag_dir, "grad_buf", grad_buf_like)
    extras_path = os.path.join(tag_dir, "extras.pkl")
    if os.path.exists(extras_path):
        with open(extras_path, "rb") as f:
            payload["extras"] = pickle.load(f)
    unrolled_print(f"Loaded checkpoint {tag_dir}")
    return payload

"""Persistent AOT compilation cache (ISSUE 6 tentpole, cache half).

Warm-up XLA compilation of the step programs is pure ``goodput_compile_s``
paid on every restart of an identical job.  This module removes it with
three cooperating layers, all of which dispatch through ordinary
``jax.jit`` — donation, async dispatch, and numerics are byte-for-byte
the no-cache path:

1. **Process program cache** — an in-process map from HLO cache key to
   the first already-built jitted fn for that exact program.  A second
   facade in the same process whose step program lowers to identical HLO
   dispatches through the first facade's fn; jax's own per-function
   executable cache then serves every call with ZERO recompilation.
   Works on every backend.
2. **XLA persistent cache** — :func:`install_persistent_xla_cache`
   points the process-global jax compilation cache at a directory, so
   backend compiles are disk-memoized across processes and a warm
   process's compiles load in milliseconds.  NON-CPU backends only: this
   jaxlib's CPU persistent cache round-trips executables through a
   serialization path that corrupts the heap for sharded/donated step
   programs (reproducible ``malloc_consolidate()`` aborts driving the
   oss/sddp/fsdp equivalence suite under an active cache), so on CPU it
   is refused and warm starts are same-process only.
3. **AOT program ledger** — :class:`CompileCache` explicitly lowers each
   step program at first dispatch, keys it by a sha256 of the **lowered
   HLO text** plus an :func:`environment_fingerprint`, and keeps a
   provenance marker per key recording the cold first-dispatch seconds.
   A warm start (via layer 1 or 2) counts a ``compile_cache_hit`` and
   credits the recorded seconds as reclaimed — feeding the goodput
   ledger's ``compile_fresh`` vs ``compile_cached`` split.  On a miss
   the compiled executable is additionally serialized
   (``jax.experimental.serialize_executable``) next to the marker as an
   offline AOT artifact (``exe-<key>.bin``) when a live XLA cache can
   absorb the extra compile.

Why the step programs do NOT dispatch through deserialized executables:
on current jax, ``deserialize_and_load`` loses the donated-input
bookkeeping — an executable with input/output buffer aliasing hands back
outputs whose producers jax no longer tracks, and chaining such calls
over carried training state can consume an aliased buffer before the
previous step materialized it (observed as silent numeric corruption on
the CPU mesh; tests/test_compile_cache.py pins the safe architecture).
The CPU persistent-cache heap corruption above is the same bookkeeping
loss surfacing inside XLA itself.

Why key on the lowered HLO and not on config metadata: the HLO *is* the
program.  Any change in model code, loss math, optimizer hyperparameters
(baked in as constants), shapes, shardings, precision, or grad-accum
structure changes the text and therefore the key — a warm start can
never be served different math, and reclaimed-seconds credit can never
be claimed for it.  What the HLO does not capture — the compiler that
will run it — is the fingerprint's job: jax/jaxlib versions, backend,
``XLA_FLAGS``, device topology, process count.

Failure policy: every cache-layer failure (serialization unsupported,
corrupt entry, filesystem error) degrades to plain compilation with a
warning and a ``serialize_errors`` count — the cache must never be what
kills a training run.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
import warnings
from typing import Any, Dict, Optional

#: cache entry filename prefix (``<prefix><sha>.json`` marker +
#: optional ``.bin`` serialized-executable artifact)
ENTRY_PREFIX = "exe-"

#: module-global: the persistent-XLA-cache directory already installed
#: (the jax knob is process-global; first caller wins)
_xla_cache_installed: set = set()
_xla_cache_lock = threading.Lock()

#: process-level program cache: HLO cache key -> the first already-built
#: jitted fn for that exact program.  A SECOND facade in the same process
#: whose program lowers to the same HLO dispatches through the first
#: facade's jit fn — jax's own per-function executable cache then serves
#: every call with ZERO recompilation, and the semantics are plain
#: ``jax.jit`` (identical HLO => identical math; donation/async exactly
#: as ever).  This is the warm-start layer that works on EVERY backend —
#: including CPU, where both jax-level serialization paths are unsafe
#: (see install_persistent_xla_cache / the module docstring).
_process_fn_cache: Dict[str, Any] = {}
_process_fn_lock = threading.Lock()
#: cap: each cached fn keeps its closure (adapter/optimizer objects)
#: alive; a bounded map keeps pathological many-model processes from
#: retaining unbounded state.  Beyond the cap new programs simply stop
#: being shareable (never an error).
_PROCESS_FN_CAP = 256

#: one CPU-refusal warning per process (every CompileConfig construction
#: re-attempts the install; the refusal reason does not change)
_cpu_refusal_warned = False

#: per-run memo cap, mirroring the engine's _MAX_SHAPE_SIGS discipline:
#: each new (program, shape signature) pays a full trace+lower plus
#: marker I/O on its first dispatch, so under pathological shape churn
#: the ledger stops engaging beyond the cap (dispatch degrades to the
#: plain jitted fn; host memory stays bounded)
_MEMO_CAP = 1024


def environment_fingerprint(
    *,
    xla_flags: Optional[str] = None,
    jax_version: Optional[str] = None,
    jaxlib_version: Optional[str] = None,
    backend: Optional[str] = None,
    topology: Optional[str] = None,
    n_processes: Optional[int] = None,
) -> str:
    """Canonical description of the compiler + topology an entry was
    built for.  Two environments with different fingerprints must never
    share cache entries even for identical HLO: the same program
    compiles differently under a different jaxlib, flag set, or device
    assignment.

    All components are overridable for tests; defaults read the live
    process.  Deterministic across processes (no ``hash()``, no ids).
    """
    if jax_version is None or jaxlib_version is None or backend is None \
            or topology is None or n_processes is None:
        import jax
        import jaxlib

        if jax_version is None:
            jax_version = jax.__version__
        if jaxlib_version is None:
            jaxlib_version = jaxlib.__version__
        if backend is None:
            backend = jax.default_backend()
        if topology is None:
            devs = jax.devices()
            topology = f"{len(devs)}x{devs[0].device_kind}"
        if n_processes is None:
            n_processes = jax.process_count()
    if xla_flags is None:
        xla_flags = os.environ.get("XLA_FLAGS", "")
    return "|".join(
        (
            "stoke-compile-cache/v1",
            jax_version,
            jaxlib_version,
            backend,
            xla_flags,
            topology,
            str(int(n_processes)),
        )
    )


def hlo_cache_key(hlo_text: str, fingerprint: str) -> str:
    """Content-addressed cache key: sha256 over the lowered program body
    and the environment fingerprint.

    The module NAME is normalized out before hashing via the SHARED
    :func:`stoke_tpu.analysis.hlo_text.normalize_module_name` (the
    program auditor consumes the same normalizer — ISSUE 15: two
    normalizers would drift): it carries the jit wrapper's function name
    plus any per-process uniquifying counter, and a renamed module is
    still the same program.  Everything else, including the mhlo
    partition/replica attributes, stays in the hash.  Stable across
    processes (tested in tests/test_compile_cache.py).
    """
    from stoke_tpu.analysis.hlo_text import normalize_module_name

    body = normalize_module_name(hlo_text)
    h = hashlib.sha256()
    h.update(fingerprint.encode())
    h.update(b"\x00")
    h.update(body.encode())
    return ENTRY_PREFIX + h.hexdigest()[:40]


def install_persistent_xla_cache(
    cache_dir: str, min_compile_time_s: float = 0.0
) -> bool:
    """Point jax's process-global persistent compilation cache at
    ``cache_dir``.  Idempotent; FIRST caller wins — re-pointing the
    global knob mid-process would strand the earlier run's entries, and
    the cache is content-addressed so sharing one directory is always
    safe.  Returns True when this directory owns the knob, False when
    another does or the runtime lacks the facility.

    REFUSED on the CPU backend: this jaxlib's CPU persistent cache
    round-trips executables through a serialization path that corrupts
    the heap for sharded/donated step programs (reproducible
    ``malloc_consolidate(): invalid chunk size`` aborts driving the
    oss/sddp/fsdp equivalence suite under an active cache) — the same
    bookkeeping loss that makes ``deserialize_and_load`` dispatch unsafe.
    CPU warm starts come from the process-level program cache instead.
    """
    with _xla_cache_lock:
        if cache_dir in _xla_cache_installed:
            return True
        if _xla_cache_installed:
            return False
        try:
            import jax

            if jax.default_backend() == "cpu":
                global _cpu_refusal_warned
                if not _cpu_refusal_warned:
                    _cpu_refusal_warned = True
                    warnings.warn(
                        "Stoke -- persistent XLA compilation cache "
                        "disabled on the CPU backend (its executable "
                        "serialization corrupts the heap for sharded/"
                        "donated programs on this jaxlib); same-process "
                        "warm starts still hit the in-process program "
                        "cache"
                    )
                return False
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            try:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs",
                    float(min_compile_time_s),
                )
            except Exception:
                pass  # knob renamed/absent: threshold stays default
            try:
                # cache small test/CPU programs too (default floor skips
                # tiny entries, which would defeat the CPU-mesh tests)
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", -1
                )
            except Exception:
                pass
            try:
                # jax latches its cache-enabled decision at the FIRST
                # backend compile — which already happened during mesh
                # build / placement before this config existed.  Reset so
                # the next compile re-initializes against the new dir
                # (without this the dir is silently never written).
                from jax.experimental.compilation_cache import (
                    compilation_cache as _cc,
                )

                _cc.reset_cache()
            except Exception:
                pass
            _xla_cache_installed.add(cache_dir)
            return True
        except Exception as e:
            warnings.warn(
                f"Stoke -- persistent XLA compilation cache unavailable "
                f"({e!r}); compile warm-starts disabled"
            )
            return False


def xla_cache_active() -> bool:
    """True when SOME persistent XLA cache directory owns the process
    knob (first-caller-wins; serving works for every run in the process
    regardless of which run installed it)."""
    return bool(_xla_cache_installed)


def active_xla_cache_dir() -> Optional[str]:
    """The directory owning the process-global persistent-cache knob
    (None when none installed).  Markers record it so a hit is only
    claimed when the cache that would serve the compile is the one the
    marker's entry was persisted into."""
    for d in _xla_cache_installed:
        return d
    return None


class CompileCache:
    """One per :class:`~stoke_tpu.facade.Stoke` run (constructed by the
    facade when a ``CompileConfig`` is supplied; the engine calls
    :meth:`executable` at each step-program dispatch site).

    Counters (registered in the run's telemetry registry, so they
    surface in snapshots / Prometheus and feed the goodput ledger's
    ``compile_fresh``/``compile_cached`` split):

    - ``compile_cache/hits_total`` / ``misses_total``: per-program AOT
      ledger lookups (a hit means the impending backend compile is
      served from the persistent cache).
    - ``compile_cache/hit_s_total``: first-dispatch wall seconds of hit
      programs — the *cached* warm-start cost actually paid (lowering +
      cache-served compile + first run).
    - ``compile_cache/saved_s_total``: the markers' recorded cold
      first-dispatch seconds — the reclaimed ``goodput_compile_s``.
    - ``compile_cache/serialize_errors_total``: artifact/marker
      degradations.
    """

    def __init__(self, cfg, registry=None):
        self.cfg = cfg
        self.registry = registry
        self.hits = 0
        self.misses = 0
        self.serialize_errors = 0
        self.saved_compile_s = 0.0
        self.fingerprint = environment_fingerprint()
        # per-run memo: (engine program key, shape signature) resolved ->
        # one ledger lookup per program signature per run; every later
        # dispatch is a dict lookup returning the jit fn untouched
        self._memo: Dict[Any, Any] = {}
        self._lock = threading.Lock()
        self._warned = False
        os.makedirs(cfg.cache_dir, exist_ok=True)
        installed = False
        if cfg.xla_cache:
            installed = install_persistent_xla_cache(
                os.path.join(cfg.cache_dir, "xla"), cfg.min_compile_time_s
            )
        # hits require a LIVE persistent cache (ours or another run's in
        # this process — the knob is global): a marker alone reclaims
        # nothing, and counting it as a hit would be a lie
        self.xla_available = installed or xla_cache_active()
        if registry is not None:
            registry.counter(
                "compile_cache/hits_total",
                help="AOT program-ledger hits (warm starts)",
            )
            registry.counter(
                "compile_cache/misses_total",
                help="AOT program-ledger misses (fresh compiles)",
            )
            registry.counter(
                "compile_cache/hit_s_total",
                help="ledger bookkeeping seconds booked on warm starts",
            )
            registry.counter(
                "compile_cache/saved_s_total",
                help="cold compile seconds reclaimed by cache hits",
            )
            registry.counter(
                "compile_cache/serialize_errors_total",
                help="cache marker/artifact degradations",
            )

    # ------------------------------------------------------------------ #
    # the engine-facing hook
    # ------------------------------------------------------------------ #

    def executable(self, program: str, memo_key, fn, args: tuple):
        """Resolve the callable for one dispatch of jitted ``fn`` at
        ``args``.  ALWAYS dispatches through a plain jitted fn
        (donation/async semantics untouched); the first call per
        ``memo_key`` lowers the program for its HLO key, checks the
        ledger, and resolves to either the process-cached already-built
        fn (warm hit — EVERY later dispatch of this signature goes
        through it too, or the hit would merely defer the recompile to
        the second dispatch) or a one-shot timing wrapper that records
        the cold first-dispatch cost as the marker's reclaimed seconds
        (miss).  Any cache failure degrades to ``fn`` untouched.
        """
        entry = self._memo.get(memo_key)
        if entry is not None:
            return entry
        with self._lock:
            entry = self._memo.get(memo_key)
            if entry is not None:
                return entry
            if len(self._memo) >= _MEMO_CAP:
                # pathological shape churn: beyond the cap new
                # signatures skip the ledger entirely (no lower, no
                # marker I/O, no memo growth) — never an error
                return fn
            if not self.cfg.aot:
                self._memo[memo_key] = fn
                return fn
            try:
                first, steady = self._first_dispatch(program, fn, args)
            except Exception as e:
                self._note_error(program, e)
                first = steady = fn
            # later dispatches of this signature bypass the ledger —
            # dispatching through the RESOLVED fn (the shared one on a
            # process-cache hit)
            self._memo[memo_key] = steady
            return first

    def _first_dispatch(self, program: str, fn, args: tuple):
        """Resolve one program's first dispatch.  Returns ``(first,
        steady)``: the callable for THIS dispatch and the one every
        later dispatch of the same signature memoizes."""
        t0 = time.perf_counter()
        lowered = fn.lower(*args)
        key = hlo_cache_key(lowered.as_text(), self.fingerprint)
        base = os.path.join(self.cfg.cache_dir, key)
        # hit accounting starts AFTER lowering: tracing/lowering happens
        # on the cold path too and is counted in neither path's compile
        # bucket — the hit seconds measure only the ledger's own
        # bookkeeping, keeping cold-vs-warm goodput_compile_s symmetric
        t_ledger = time.perf_counter()
        meta = self._read_marker(base)
        # layer A — process program cache: a facade in THIS process
        # already built the identical program; dispatch through its jit
        # fn (already compiled, plain jit semantics) — zero recompile on
        # any backend
        with _process_fn_lock:
            shared = _process_fn_cache.get(key)
        if shared is not None:
            self._book_hit(meta, t_ledger)
            return shared, shared
        # layer B — persistent XLA cache (non-CPU backends): the marker
        # proves this exact program's compile was persisted, and only
        # when the LIVE cache is the one it was persisted into — markers
        # pointing at a different (or no) XLA cache dir would claim
        # reclaimed seconds while the backend compile runs full codegen
        if (
            meta is not None
            and self.xla_available
            and meta.get("xla_cache_dir") == active_xla_cache_dir()
        ):
            self._book_hit(meta, t_ledger)
            self._publish(key, fn)
            return fn, fn
        self.misses += 1
        self._inc("compile_cache/misses_total")

        def first_call_miss(*a):
            out = fn(*a)
            # the marker's cold cost: lowering + XLA compile + first run
            # (compile-dominated for real step programs) — what a warm
            # start reclaims
            self._write_marker(
                base, program, time.perf_counter() - t0, lowered
            )
            self._publish(key, fn)
            return out

        return first_call_miss, fn

    def _book_hit(self, meta: Optional[Dict[str, Any]], t0: float) -> None:
        """Account one warm start: the hit count, the reclaimed seconds
        the marker recorded, and the ledger's own bookkeeping seconds
        (marker read + lookup — measured after lowering and before
        dispatch, so neither tracing nor step execution ever lands in
        the compile accounting)."""
        self.hits += 1
        self._inc("compile_cache/hits_total")
        self._inc("compile_cache/hit_s_total", time.perf_counter() - t0)
        if meta is not None:
            saved = float(meta.get("compile_time_s", 0.0))
            self.saved_compile_s += saved
            self._inc("compile_cache/saved_s_total", saved)

    @staticmethod
    def _publish(key: str, fn) -> None:
        with _process_fn_lock:
            if len(_process_fn_cache) < _PROCESS_FN_CAP:
                _process_fn_cache.setdefault(key, fn)

    # ------------------------------------------------------------------ #
    # ledger entries
    # ------------------------------------------------------------------ #

    def _read_marker(self, base: str) -> Optional[Dict[str, Any]]:
        try:
            with open(base + ".json") as f:
                return json.load(f)
        except OSError:
            return None
        except ValueError as e:  # corrupt marker: a miss, not a crash
            self._note_error("marker", e, what="read")
            return None

    def _write_marker(self, base: str, program: str, cold_s: float,
                      lowered) -> None:
        """Persist the provenance marker (atomic tmp + rename, pid-unique
        so processes racing on the same content-addressed entry cannot
        torn-write) and — best effort — the serialized executable
        artifact for offline AOT use."""
        try:
            meta = {
                "program": program,
                "compile_time_s": round(cold_s, 6),
                "created": time.strftime("%Y-%m-%dT%H:%M:%S"),
                "fingerprint": self.fingerprint,
                # the persistent cache this compile landed in — a later
                # run only claims a hit when the SAME cache will serve it
                "xla_cache_dir": active_xla_cache_dir(),
            }
            tmp = f"{base}.json.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(meta, f, indent=2)
            os.replace(tmp, base + ".json")
        except Exception as e:
            self._note_error(program, e, what="marker write")
            return
        if not self.cfg.serialize_executables:
            return
        try:
            from jax.experimental.serialize_executable import serialize

            # the jit call just populated the persistent cache, so this
            # extra compile is served from disk (cheap); without a live
            # cache — or when the compile fell below the persistence
            # threshold and was therefore NOT cached (cold_s bounds the
            # compile time from above) — it would re-run full codegen
            # and double the cold start — skip
            if not self.xla_available:
                return
            if (
                self.cfg.min_compile_time_s > 0
                and cold_s < self.cfg.min_compile_time_s
            ):
                return
            payload, in_tree, out_tree = serialize(lowered.compile())
            tmp = f"{base}.bin.{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                pickle.dump((payload, in_tree, out_tree), f)
            os.replace(tmp, base + ".bin")
        except Exception as e:
            self._note_error(program, e, what="artifact serialize")

    def deserialize(self, key: str):
        """Load a serialized executable artifact for OFFLINE one-shot
        use (inspection, export, replay with ready inputs).  Do NOT
        drive a training loop's carried state through the result: on
        current jax a deserialized executable loses donated-input
        bookkeeping, and chaining calls over aliased state buffers races
        their producers (the module docstring pins the evidence).
        Loadability is backend-dependent — the CPU backend cannot always
        reload executables whose compile was itself served from the
        persistent cache ("Symbols not found"); callers must treat a
        raising deserialize as "artifact unusable on this backend"."""
        from jax.experimental.serialize_executable import (
            deserialize_and_load,
        )

        with open(os.path.join(self.cfg.cache_dir, key + ".bin"), "rb") as f:
            payload, in_tree, out_tree = pickle.load(f)
        return deserialize_and_load(payload, in_tree, out_tree)

    # ------------------------------------------------------------------ #
    # bookkeeping
    # ------------------------------------------------------------------ #

    def _inc(self, name: str, value: float = 1.0) -> None:
        if self.registry is not None:
            self.registry.counter(name).inc(value)

    def _note_error(self, program: str, err, what: str = "cache") -> None:
        self.serialize_errors += 1
        self._inc("compile_cache/serialize_errors_total")
        if not self._warned:
            self._warned = True
            warnings.warn(
                f"Stoke -- compile cache {what} failed for program "
                f"{program!r}: {err!r}; degrading to plain compilation "
                f"(warned once per run)"
            )

    def stats(self) -> Dict[str, Any]:
        """Run-level cache accounting (also the ``Stoke.compile_cache``
        surface the bench ``--tuned`` arm records)."""
        return {
            "hits": self.hits,
            "misses": self.misses,
            "saved_compile_s": round(self.saved_compile_s, 6),
            "serialize_errors": self.serialize_errors,
            "cache_dir": self.cfg.cache_dir,
            "xla_cache_active": self.xla_available,
            "entries": len(self._memo),
        }

"""Pytree helpers: the TPU equivalents of the reference's tensor plumbing.

Reference counterparts:
- ``place_data_on_gpu`` recursive tensor mover (stoke/utils.py:39-80) →
  :func:`place_data_on_device` (host batch → device/sharded jax arrays).
- ``zero_optimizer_grads`` (stoke/utils.py:83-106) → grads live in an explicit
  accumulation pytree; "zeroing" is :func:`tree_zeros_like` inside the compiled
  apply step (no eager ``.grad`` attributes to clear).
- parameter counting for ``num_model_parameters`` (stoke/stoke.py:1144-1162) →
  :func:`tree_count_params`.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


def tree_count_params(tree: Any) -> int:
    """Total number of elements across all leaves (reference param-count
    helper, stoke.py:1144-1162)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return int(sum(np.prod(l.shape) if hasattr(l, "shape") else 1 for l in leaves))


def tree_cast(tree: Any, dtype) -> Any:
    """Cast all inexact (floating) leaves to ``dtype``; leave integer/bool
    leaves untouched (the bf16 compute-policy cast, SURVEY.md §7)."""
    if dtype is None:
        return tree

    def _cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.inexact):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(_cast, tree)


def tree_zeros_like(tree: Any) -> Any:
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def tree_add(a: Any, b: Any) -> Any:
    return jax.tree_util.tree_map(jnp.add, a, b)


def tree_scale(tree: Any, scalar) -> Any:
    return jax.tree_util.tree_map(lambda x: x * scalar, tree)


def tree_finite(tree: Any):
    """Scalar bool: True iff every element of every leaf is finite (the
    functional replacement for GradScaler's inf/nan found check,
    reference fp16.py:788-806)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    finites = [jnp.all(jnp.isfinite(l)) for l in leaves]
    return jnp.stack(finites).all()


def _to_host_array(x: Any) -> Any:
    """Torch tensor / list / scalar → numpy (host side, zero-copy for torch
    CPU tensors)."""
    if hasattr(x, "detach") and hasattr(x, "numpy"):  # torch.Tensor, no import
        return x.detach().cpu().numpy()
    if isinstance(x, np.ndarray):
        return x
    return np.asarray(x)


def place_data_on_device(batch: Any, sharding: Optional[Any] = None) -> Any:
    """Recursively move a host batch (torch tensors / numpy / nested
    list/tuple/dict) onto device, optionally with a NamedSharding so the global
    batch lands sharded over the mesh data axis.

    TPU-native replacement for ``place_data_on_gpu`` (stoke/utils.py:39-80):
    instead of per-rank ``.cuda()`` calls, one host process places its slice of
    the logically-global batch and XLA addresses it via the sharding.
    """

    def _place(x):
        arr = _to_host_array(x)
        if sharding is not None:
            return jax.device_put(arr, sharding)
        return jax.device_put(arr)

    return jax.tree_util.tree_map(
        _place, batch, is_leaf=lambda x: hasattr(x, "detach") or hasattr(x, "shape")
    )


def to_numpy_tree(tree: Any) -> Any:
    """Device pytree → host numpy pytree (checkpoint consolidation path,
    reference io_ops.py:160-243 state_dict gather)."""
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)

"""Native TensorBoard event writer — no torch, no tensorflow.

The reference logs metrics only through DeepSpeed's tensorboard passthrough
(reference configs.py:392-405); round 2 used ``torch.utils.tensorboard``,
which drags the whole torch runtime in for what is a ~100-line file format
(VERDICT r2 weak #7).  This writes the format directly:

- **TFRecord framing**: ``[uint64 len][u32 masked_crc(len)][payload]
  [u32 masked_crc(payload)]`` per record, CRC32C (Castagnoli) with
  TensorFlow's mask rotation.
- **Event protobuf**, hand-encoded (the wire format is stable and tiny):
  ``Event{wall_time(1,double), step(2,varint), file_version(3,string) |
  summary(5,msg)}``; ``Summary{value(1,msg)}``;
  ``Summary.Value{tag(1,string), simple_value(2,float)}``.

Files named ``events.out.tfevents.<ts>.<host>`` under the log dir, exactly
what TensorBoard's loader globs for.  Compatibility is pinned by
tests/test_utils.py, which reads the file back with the real ``tensorboard``
package loader when available (and a standalone frame parser otherwise).
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Optional

# --------------------------------------------------------------------------- #
# CRC32C (Castagnoli, reflected poly 0x82F63B78) + TF masking
# --------------------------------------------------------------------------- #

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# --------------------------------------------------------------------------- #
# minimal protobuf wire encoding
# --------------------------------------------------------------------------- #


def _varint(n: int) -> bytes:
    # proto int64 convention: negatives encode as the 64-bit two's
    # complement (10-byte varint) — without the mask a negative n would
    # loop forever (-1 >> 7 == -1 in Python)
    n &= (1 << 64) - 1
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _key(field: int, wire_type: int) -> bytes:
    return _varint((field << 3) | wire_type)


def _double_field(field: int, value: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", value)


def _float_field(field: int, value: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", value)


def _varint_field(field: int, value: int) -> bytes:
    return _key(field, 0) + _varint(value)


def _bytes_field(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _scalar_event(tag: str, value: float, step: int, wall_time: float) -> bytes:
    val = _bytes_field(1, tag.encode("utf-8")) + _float_field(2, float(value))
    summary = _bytes_field(1, val)
    return (
        _double_field(1, wall_time)
        + _varint_field(2, int(step))
        + _bytes_field(5, summary)
    )


def _version_event(wall_time: float) -> bytes:
    return _double_field(1, wall_time) + _bytes_field(
        3, b"brain.Event:2"
    )


# --------------------------------------------------------------------------- #
# writer
# --------------------------------------------------------------------------- #


class TBEventWriter:
    """Append-only scalar event writer for one log directory.

    Drop-in for the ``add_scalar``/``flush``/``close`` subset of
    ``torch.utils.tensorboard.SummaryWriter`` the facade uses."""

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        fname = (
            f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
            f".{os.getpid()}"
        )
        self.path = os.path.join(logdir, fname)
        self._f = open(self.path, "ab")
        self._lock = threading.Lock()
        self._write_record(_version_event(time.time()))
        self.flush()

    def _write_record(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        with self._lock:
            self._f.write(header)
            self._f.write(struct.pack("<I", _masked_crc(header)))
            self._f.write(payload)
            self._f.write(struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag: str, value: float,
                   step: Optional[int] = None) -> None:
        self._write_record(
            _scalar_event(tag, value, step or 0, time.time())
        )

    def flush(self) -> None:
        with self._lock:
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


def read_scalar_events(path: str):
    """Parse a TB event file back into ``[(tag, value, step), ...]`` —
    the verification half of the format contract (CRC-checked)."""
    out = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                break
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", f.read(4))
            if hcrc != _masked_crc(header):
                raise ValueError(f"{path}: corrupt record header")
            payload = f.read(length)
            (pcrc,) = struct.unpack("<I", f.read(4))
            if pcrc != _masked_crc(payload):
                raise ValueError(f"{path}: corrupt record payload")
            out.extend(_parse_event(payload))
    return out


def _parse_fields(buf: bytes):
    i = 0
    while i < len(buf):
        key = 0
        shift = 0
        while True:
            b = buf[i]
            i += 1
            key |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        field, wt = key >> 3, key & 7
        if wt == 0:
            val = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                val |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
        elif wt == 1:
            val = buf[i:i + 8]
            i += 8
        elif wt == 2:
            ln = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            val = buf[i:i + ln]
            i += ln
        elif wt == 5:
            val = buf[i:i + 4]
            i += 4
        else:  # pragma: no cover - not produced by this writer
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def _parse_event(payload: bytes):
    step = 0
    scalars = []
    for field, wt, val in _parse_fields(payload):
        if field == 2 and wt == 0:
            step = val
        elif field == 5 and wt == 2:  # summary
            for f2, w2, v2 in _parse_fields(val):
                if f2 == 1 and w2 == 2:  # value
                    tag, num = None, None
                    for f3, w3, v3 in _parse_fields(v2):
                        if f3 == 1 and w3 == 2:
                            tag = v3.decode("utf-8")
                        elif f3 == 2 and w3 == 5:
                            (num,) = struct.unpack("<f", v3)
                    if tag is not None and num is not None:
                        scalars.append((tag, num, step))
    return scalars

"""Printing / filesystem helpers (reference: stoke/utils.py:109-151)."""

from __future__ import annotations

import os
from typing import Any, Iterable, Union


def unrolled_print(value: Union[str, Iterable[Any]], single_line: bool = False) -> None:
    """Print strings or iterables of strings with the ``Stoke --`` prefix
    (reference ``unrolled_print``, stoke/utils.py:109-134)."""
    if isinstance(value, str):
        print(f"Stoke -- {value}")
        return
    items = list(value)
    if single_line:
        print("Stoke -- " + ", ".join(str(v) for v in items))
    else:
        for v in items:
            print(f"Stoke -- {v}")


def make_folder(path: str) -> str:
    """Create a directory if needed, returning the absolute path
    (reference ``make_folder``, stoke/utils.py:137-151)."""
    path = os.path.abspath(os.path.expanduser(path))
    os.makedirs(path, exist_ok=True)
    return path

"""Model initialization helpers.

``flax.linen.Module.init`` run eagerly executes hundreds of small ops on the
default backend; on a remote/tunneled TPU each op pays a round trip and init
takes minutes.  :func:`init_module` runs the whole init as ONE compiled
program on the host CPU — the facade then places the result onto the mesh
according to the sharding rules, so no device ever holds more than its shard
(plus the host copy)."""

from __future__ import annotations

from typing import Any

import jax


def force_cpu() -> None:
    """Restrict THIS process to the JAX CPU backend.

    Call before building a ``Stoke`` (and before ANY jax computation) when
    you want a pure-CPU run on a machine whose accelerator backend is broken
    or unreachable (a wedged remote-TPU tunnel hangs any code that lets JAX
    enumerate backends).  Works even when jax was already imported
    (config-level, not env) — but NOT once a backend has initialized: the
    platform restriction would silently be a no-op, so that case raises.
    """
    try:
        from jax._src import xla_bridge as _xb

        initialized = bool(getattr(_xb, "_backends", {}))
    except Exception:
        initialized = False
    if initialized:
        raise RuntimeError(
            "stoke_tpu.force_cpu() must run before any JAX computation: a "
            "backend is already initialized and the platform restriction "
            "would silently have no effect"
        )
    jax.config.update("jax_platforms", "cpu")


def init_module(module, rng, *args, **kwargs) -> Any:
    """Initialize a flax module's variables host-side in one compiled call.

    Usage:
        variables = init_module(model, jax.random.PRNGKey(0), dummy_batch,
                                train=False)
    """
    # local_devices, not devices: in a multi-process run the global device
    # list leads with process 0's devices, which other processes cannot
    # address (device_put would raise "non-addressable device")
    cpu = jax.local_devices(backend="cpu")[0]
    rng = jax.device_put(rng, cpu)
    with jax.default_device(cpu):
        return jax.jit(lambda r: module.init(r, *args, **kwargs))(rng)

"""Declarative YAML/dict → Stoke construction.

The reference's example layer drives stoke with the ``spock`` YAML config
library (examples/cifar10/train.py:60-62, configs.py:15-85); here the
equivalent is a framework utility: one document describes every flag and
config object, so experiments switch context by pointing at a different
file (the reference demo story, README.md:13-20).

Schema (all keys optional except batch_size_per_device):

    batch_size_per_device: 64
    grad_accum: 2
    device: tpu
    distributed: dp
    precision: bf16
    oss: false
    sddp: false
    fsdp: true
    grad_clip: {type: norm, max_norm: 1.0}        # or {type: value, clip_value: 0.5}
    optimizer: {name: adamw, learning_rate: 3.0e-4}
    seed: 0
    ema_weight: 0.1
    configs:                                       # config objects by class name
      FSDPConfig: {min_weight_size: 4096}
      MeshConfig: {axes: [data, model], shape: [-1, 2]}
      CheckpointConfig: {format: sharded, save_every_n_steps: 500,
                         auto_path: ckpts/auto}
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

from stoke_tpu.configs import (
    ALL_CONFIG_CLASSES,
    CheckpointFormat,
    ClipGradConfig,
    ClipGradNormConfig,
    LossReduction,
)

_CONFIG_BY_NAME = {cls.__name__: cls for cls in ALL_CONFIG_CLASSES}
# enum-valued fields that arrive as strings from YAML
_ENUM_FIELDS = {"format": CheckpointFormat, "loss_reduction": LossReduction}

_STOKE_FLAG_KEYS = (
    "batch_size_per_device", "grad_accum", "device", "distributed",
    "precision", "oss", "sddp", "fsdp", "seed", "ema_weight", "verbose",
    "model_train_kwargs", "model_eval_kwargs", "model_rng_keys",
)


def _build_grad_clip(spec: Optional[Dict[str, Any]]):
    if spec is None:
        return None
    spec = dict(spec)
    kind = spec.pop("type", "norm")
    if kind in ("norm", "clip_norm"):
        return ClipGradNormConfig(**spec)
    if kind in ("value", "clip_value"):
        return ClipGradConfig(**spec)
    raise ValueError(f"Stoke -- unknown grad_clip type {kind!r}")


def _build_optimizer(spec: Optional[Dict[str, Any]]):
    if spec is None:
        return None
    import optax

    spec = dict(spec)
    name = spec.pop("name")
    ctor = getattr(optax, name, None)
    if ctor is None:
        raise ValueError(f"Stoke -- optax has no optimizer named {name!r}")
    return {"optimizer": ctor, "optimizer_kwargs": spec}


def _build_config_object(name: str, fields: Dict[str, Any]):
    cls = _CONFIG_BY_NAME.get(name)
    if cls is None:
        raise ValueError(
            f"Stoke -- unknown config class {name!r}; valid: "
            f"{sorted(_CONFIG_BY_NAME)}"
        )
    fields = dict(fields or {})
    for key, enum_cls in _ENUM_FIELDS.items():
        if key in fields and isinstance(fields[key], str):
            fields[key] = enum_cls(fields[key])
    # YAML lists → tuples for tuple-typed fields (axes, shape, rules, ...)
    for k, v in fields.items():
        if isinstance(v, list):
            fields[k] = tuple(tuple(i) if isinstance(i, list) else i for i in v)
    return cls(**fields)


def stoke_kwargs_from_config(cfg: Union[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Translate a YAML path / dict into ``Stoke(**kwargs)`` keyword args
    (everything except model/loss/params).  Unknown top-level keys raise —
    typos should not silently train a different run."""
    if isinstance(cfg, str):
        import yaml

        with open(cfg) as f:
            cfg = yaml.safe_load(f)
    cfg = dict(cfg or {})
    out: Dict[str, Any] = {}
    for key in _STOKE_FLAG_KEYS:
        if key in cfg:
            out[key] = cfg.pop(key)
    if "grad_clip" in cfg:
        out["grad_clip"] = _build_grad_clip(cfg.pop("grad_clip"))
    if "optimizer" in cfg:
        out["optimizer"] = _build_optimizer(cfg.pop("optimizer"))
    if "configs" in cfg:
        out["configs"] = [
            _build_config_object(name, fields)
            for name, fields in (cfg.pop("configs") or {}).items()
        ]
    if cfg:
        raise ValueError(f"Stoke -- unknown config keys: {sorted(cfg)}")
    return out


def stoke_from_config(
    model: Any,
    loss: Any,
    params: Any,
    cfg: Union[str, Dict[str, Any]],
    optimizer: Any = None,
    **overrides,
):
    """Build a :class:`~stoke_tpu.Stoke` from a YAML file / dict.

    ``optimizer`` may come from the document (``optimizer: {name: ...}``) or
    be passed explicitly (explicit wins).  ``overrides`` are applied last.
    """
    from stoke_tpu import Stoke

    kwargs = stoke_kwargs_from_config(cfg)
    if optimizer is not None:
        kwargs["optimizer"] = optimizer
    if "optimizer" not in kwargs:
        raise ValueError(
            "Stoke -- no optimizer: add an `optimizer:` section to the config "
            "or pass one explicitly"
        )
    kwargs.update(overrides)
    return Stoke(model=model, loss=loss, params=params, **kwargs)

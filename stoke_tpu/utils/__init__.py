"""Utility helpers (reference: stoke/utils.py:1-151, TPU-native re-design)."""

from stoke_tpu.utils.init import force_cpu, init_module
from stoke_tpu.utils.yaml_config import stoke_from_config, stoke_kwargs_from_config
from stoke_tpu.utils.printing import unrolled_print, make_folder
from stoke_tpu.utils.trees import (
    tree_count_params,
    tree_cast,
    tree_zeros_like,
    tree_add,
    tree_scale,
    tree_finite,
    place_data_on_device,
    to_numpy_tree,
)

__all__ = [
    "force_cpu",
    "init_module",
    "stoke_from_config",
    "stoke_kwargs_from_config",
    "unrolled_print",
    "make_folder",
    "tree_count_params",
    "tree_cast",
    "tree_zeros_like",
    "tree_add",
    "tree_scale",
    "tree_finite",
    "place_data_on_device",
    "to_numpy_tree",
]
